"""Pallas TPU flash attention: blockwise online-softmax, fwd + custom-VJP bwd.

Replaces the O(T^2)-HBM attention the reference materializes per head
(GPT1.py:114-116) with a fused kernel that keeps only (block_q, block_k)
score tiles in VMEM. Forward follows the standard flash algorithm (running
max m, running normalizer l, rescaled accumulator); backward recomputes
score tiles blockwise from the saved logsumexp, producing dq in a q-major
kernel and dk/dv in a kv-major kernel (no stored attention matrix anywhere).

Layout notes (TPU): q/do tiles are (block, D) with D in {32, 64, 128,
256} and block auto-sized to the largest of {512, 256, 128} dividing T
(``_auto_block`` — 512x512 score tiles measured 2.3x faster fwd+bwd than
128x128 on v5e; callers may override). LSE/delta are per-row scalars,
which Mosaic cannot tile as a bare (T,) lane — they are carried
broadcast across a LANES-wide trailing dim ((BH, T, LANES) arrays,
(block_q, LANES) tiles), the same layout the reference TPU flash kernel
in jax.experimental.pallas.ops.tpu uses for its m/l stats.
Causal masking skips fully-masked kv blocks entirely (the fori_loop upper
bound is derived from the q-block index), so the kernel does ~half the
FLOPs of the dense path on causal workloads.

Two kernel families, auto-selected by K/V footprint (STREAM_KV_BYTES):
the resident kernels above hold one (batch, head)'s full (T, D) K/V in
VMEM and carry the online-softmax state in registers across a fori_loop
(fastest while it fits; Mosaic stops allocating it around T=32k for
D=64 bf16); the streamed kernels put the kv axis on the pallas grid and
carry the state in VMEM scratch, so VMEM use is O(block^2) and T is
bounded by HBM only — with a scalar-prefetched triangular tile map for
causal runs that skips masked tiles' fetches and grid steps entirely.
The families share their tile math (_fwd_tile/_dq_tile/_dkv_tile — one
source of truth, identical ops in identical order) and the counter-based
dropout mask keys off absolute positions, so their outputs are
bit-identical: measured exactly equal on v5e hardware, and
test_stream_dropout_matches_resident asserts exact equality in
interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

BLOCK = 128
LANES = 128  # trailing width for per-row stats (Mosaic lane alignment)
NEG_INF = -1e30


def _vmem_spec(block_shape, index_map):
    kw = {"memory_space": _VMEM} if _VMEM is not None else {}
    return pl.BlockSpec(block_shape, index_map, **kw)


def _smem_spec():
    kw = {"memory_space": pltpu.SMEM} if pltpu is not None else {}
    return pl.BlockSpec(**kw)


# ---------------------------------------------------------------------------
# in-kernel dropout bits
#
# Counter-based hash instead of pltpu.prng_*: the mask for tile
# (bh, q-block, k-block) must be regenerated bit-identically by three
# different kernels (fwd, bwd-dq, bwd-dkv) whose loop structures differ,
# and must also run under the CPU interpreter (prng_seed has no CPU
# lowering). Two murmur3 fmix32 rounds chained over (seed^bh, qpos, kpos)
# give full avalanche per element at a handful of VPU integer ops — noise
# quality is plenty for dropout, and tests pin the keep-rate statistics.
# ---------------------------------------------------------------------------

def _fmix32(x: jnp.ndarray) -> jnp.ndarray:
    # murmur3 finalizer; uint32 arithmetic wraps mod 2^32
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _dropout_mult(seed, bh, q_first, k_first, block_q, block_k, rate):
    """(block_q, block_k) float32 tile of {0, 1/(1-q)} — inverted
    dropout on attention weights, deterministic in (seed, bh, q, k).

    The rate quantizes to the same 1/256 granularity as every other
    dropout site (ops.attention.quantize_dropout_rate), so the flash
    path applies the identical effective rate as the einsum path the
    'auto' router may pick instead."""
    from .attention import quantize_dropout_rate
    qpos = (jnp.asarray(q_first).astype(jnp.uint32)
            + jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 0))
    kpos = (jnp.asarray(k_first).astype(jnp.uint32)
            + jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 1))
    h = _fmix32(jnp.asarray(seed).astype(jnp.uint32)
                ^ (jnp.asarray(bh).astype(jnp.uint32)
                   * jnp.uint32(0x9E3779B9)))
    y = _fmix32(_fmix32(h ^ qpos) ^ kpos)
    q = quantize_dropout_rate(rate)
    threshold = jnp.uint32(int(q * 256) * 2**24)  # q * 2^32, exact
    return jnp.where(y > threshold, jnp.float32(1.0 / (1.0 - q)),
                     jnp.float32(0.0))


# ---------------------------------------------------------------------------
# shared tile math
#
# One source of truth for the score/mask/online-softmax/gradient tile
# updates. Every kernel family (resident fori_loop, rectangular stream,
# triangular stream) wraps these on plain (block_q, ...) arrays — only
# how the operands arrive (refs, loop carries, VMEM scratch) differs.
# Keeping the math in one place is also what makes the families
# bit-identical: identical ops in identical order.
# ---------------------------------------------------------------------------


def _causal_mask(s, q_first, k_first, block_q, block_k):
    qpos = q_first + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = k_first + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(kpos <= qpos, s, NEG_INF)


def _fwd_tile(q, k, v, acc, m, l, *, scale, causal, q_first, k_first,
              block_q, block_k, seed, bh, dropout_rate):
    """One online-softmax update: returns (acc', m', l'). The softmax
    normalizer l is dropout-free (dense-path semantics: dropout applies
    to the normalized weights); only the V accumulation sees the
    inverted-dropout multiplier.

    Matmuls run on the operands' native dtype (bf16 inputs hit the MXU's
    bf16 path — ~4x the f32 rate) with f32 accumulation
    (preferred_element_type); scaling, max/exp and the normalizer stay
    f32. The probability tile is cast back to the value dtype for the
    p@v matmul — the standard flash-kernel trade (weights are in [0, 1],
    so the cast costs ~3 relative digits on an already-bf16 pipeline)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, q_first, k_first, block_q, block_k)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    if dropout_rate > 0.0:
        p = p * _dropout_mult(seed, bh, q_first, k_first, block_q, block_k,
                              dropout_rate)
    acc_new = acc * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


def _dq_tile(q, k, v, do, lse, delta, *, scale, causal, q_first, k_first,
             block_q, block_k, seed, bh, dropout_rate):
    """dq contribution of one (q-block, kv-block) tile. d(softmax):
    ds_ij = p_ij (z_ij dp_ij - delta_i); delta (the do.o rowsum) already
    absorbs the dropout mask z from forward. Matmuls on native dtype
    with f32 accumulation (see _fwd_tile)."""
    z = (_dropout_mult(seed, bh, q_first, k_first, block_q, block_k,
                       dropout_rate) if dropout_rate > 0.0 else None)
    p = _bwd_p_tile(q, k, lse, scale=scale, causal=causal, q_first=q_first,
                    k_first=k_first, block_q=block_q, block_k=block_k)
    ds = _bwd_ds_tile(p, do, v, delta, scale=scale, z=z)
    return jax.lax.dot_general(ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _bwd_p_tile(q, k, lse, *, scale, causal, q_first, k_first, block_q,
                block_k):
    """Recompute one probability tile from the forward's lse — the shared
    first half of every backward tile (split dq / dkv kernels and the
    fused single-tile kernel all call this; keep it the one source of
    truth for the score/mask/exp math)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, q_first, k_first, block_q, block_k)
    return jnp.exp(s - lse)


def _bwd_ds_tile(p, do, v, delta, *, scale, z):
    """d(softmax) tile ds = p (z dp - delta) scale — the shared second
    half (see _bwd_p_tile). ``z`` is the inverted-dropout multiplier or
    None; callers cast ds to the operand dtype at their final matmuls."""
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if z is not None:
        dp = dp * z
    return p * (dp - delta) * scale


def _dkv_tile(q, k, v, do, lse, delta, *, scale, causal, q_first, k_first,
              block_q, block_k, seed, bh, dropout_rate):
    """(dk, dv) contributions of one tile, plus the ds tile (cast to the
    operand dtype) so fully-fused callers can derive dq from the same
    recompute. The dropout stream keys off absolute (seed, bh, q-pos,
    k-pos), so kv-major loops regenerate the exact forward mask. Matmuls
    on native dtype with f32 accumulation (see _fwd_tile)."""
    z = (_dropout_mult(seed, bh, q_first, k_first, block_q, block_k,
                       dropout_rate) if dropout_rate > 0.0 else None)
    p = _bwd_p_tile(q, k, lse, scale=scale, causal=causal, q_first=q_first,
                    k_first=k_first, block_q=block_q, block_k=block_k)
    dv_c = jax.lax.dot_general(
        (p * z if z is not None else p).astype(do.dtype), do,
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dsc = _bwd_ds_tile(p, do, v, delta, scale=scale, z=z).astype(q.dtype)
    dk_c = jax.lax.dot_general(dsc, q, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    return dk_c, dv_c, dsc


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                causal, seq_len, block_q, block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[...]                                      # (bq, D) native dtype
    D = q.shape[-1]
    q_first = j * block_q

    if causal:
        n_kv = (q_first + block_q + block_k - 1) // block_k
    else:
        n_kv = seq_len // block_k

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        return _fwd_tile(q, k, v, acc, m, l, scale=scale, causal=causal,
                         q_first=q_first, k_first=kb * block_k,
                         block_q=block_q, block_k=block_k, seed=seed_ref[0],
                         bh=i, dropout_rate=dropout_rate)

    acc = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kv, body, (acc, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l), (block_q, LANES))


def _flash_fwd(q, k, v, seed, scale, causal, block_q, block_k,
               dropout_rate):
    B, H, T, D = q.shape
    BH = B * H
    qf = q.reshape(BH, T, D)
    kf = k.reshape(BH, T, D)
    vf = v.reshape(BH, T, D)
    grid = (BH, T // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               seq_len=T, block_q=block_q, block_k=block_k,
                               dropout_rate=dropout_rate)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(seed, qf, kf, vf)
    return o.reshape(B, H, T, D), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, scale, causal, seq_len, block_q,
                   block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[...]                                       # (bq, D) native dtype
    do = do_ref[...]
    lse = lse_ref[...][:, :1]                            # (bq, 1) of (bq, LANES)
    delta = delta_ref[...][:, :1]
    q_first = j * block_q
    if causal:
        n_kv = (q_first + block_q + block_k - 1) // block_k
    else:
        n_kv = seq_len // block_k

    def body(kb, dq):
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        return dq + _dq_tile(q, k, v, do, lse, delta, scale=scale,
                             causal=causal, q_first=q_first,
                             k_first=kb * block_k, block_q=block_q,
                             block_k=block_k, seed=seed_ref[0], bh=i,
                             dropout_rate=dropout_rate)

    dq = jax.lax.fori_loop(0, n_kv,
                           body, jnp.zeros(q.shape, jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, *, scale, causal, seq_len,
                    block_q, block_k, dropout_rate):
    i = pl.program_id(0)
    kb = pl.program_id(1)
    k = k_ref[...]                                       # (bk, D) native dtype
    v = v_ref[...]
    k_first = kb * block_k
    n_q = seq_len // block_q
    first_q = (k_first // block_q) if causal else 0

    def body(jb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(jb * block_q, block_q), :]
        do = do_ref[pl.ds(jb * block_q, block_q), :]
        lse = lse_ref[pl.ds(jb * block_q, block_q), :][:, :1]
        delta = delta_ref[pl.ds(jb * block_q, block_q), :][:, :1]
        dk_c, dv_c, _ = _dkv_tile(q, k, v, do, lse, delta, scale=scale,
                               causal=causal, q_first=jb * block_q,
                               k_first=k_first, block_q=block_q,
                               block_k=block_k, seed=seed_ref[0], bh=i,
                               dropout_rate=dropout_rate)
        return dk + dk_c, dv + dv_c

    dk0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv0 = jnp.zeros_like(dk0)
    dk, dv = jax.lax.fori_loop(first_q, n_q, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_fused_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dk_ref, dv_ref, *, scale, causal,
                      block_q, block_k, dropout_rate):
    """Single-tile fused backward (T == block_q == block_k): the score /
    probability tile is computed once and dq, dk AND dv all come from it —
    one kernel launch and one s/p recompute instead of two of each. At
    short T the per-step cost is launch- and recompute-bound (traced on
    v5e: 12 bwd launches were 23% of the char-GPT step), which is exactly
    what this halves. Same dropout stream as the split kernels
    (seed, bh, q_first=0, k_first=0), so fused and split backwards see the
    forward's mask."""
    i = pl.program_id(0)
    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    do = do_ref[...]
    lse = lse_ref[...][:, :1]
    delta = delta_ref[...][:, :1]
    dk, dv, dsc = _dkv_tile(q, k, v, do, lse, delta, scale=scale,
                            causal=causal, q_first=0, k_first=0,
                            block_q=block_q, block_k=block_k,
                            seed=seed_ref[0], bh=i,
                            dropout_rate=dropout_rate)
    dq = jax.lax.dot_general(dsc, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dq_ref[...] = dq.astype(dq_ref.dtype)
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused(scale, causal, block_q, block_k, dropout_rate,
                     seed, qf, kf, vf, gf, lse, delta, BH, T, D, dtype):
    kernel = functools.partial(
        _bwd_fused_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    spec_td = _vmem_spec((None, T, D), lambda i: (i, 0, 0))
    spec_tl = _vmem_spec((None, T, LANES), lambda i: (i, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(BH,),
        in_specs=[_smem_spec(), spec_td, spec_td, spec_td, spec_td,
                  spec_tl, spec_tl],
        out_specs=[spec_td, spec_td, spec_td],
        out_shape=[jax.ShapeDtypeStruct((BH, T, D), dtype)] * 3,
        interpret=_interpret_mode(),
    )(seed, qf, kf, vf, gf, lse, delta)


# dq scratch bound for the kv-major fused backward. The kernel's VMEM
# footprint per program is the full-T q/do/lse/delta blocks (~1.3 kB/row
# at D=64) PLUS this (T, D) f32 scratch and the full-T dq output block;
# 1 MiB of scratch (T<=4096 at D=64) keeps the total comfortably inside
# what the resident family is measured to compile, and leaves the split
# kernels reachable for longer resident sequences (T in (4k, 16k])
FUSED_DQ_SCRATCH_BYTES = 1024 * 1024


def _fused_kv_major_bwd(scale, causal, block_q, block_k, dropout_rate,
                        seed, offs, qf, kf, vf, gf, lse, delta,
                        BH, Tq, Tk, D, dtype):
    """Shared kv-major fully-fused backward launch: one kernel computes
    dq, dk AND dv with a (Tq, D) f32 dq scratch (see
    _chunk_bwd_fused_kernel). The resident family is exactly the
    offs == (0, 0, 0), Tq == Tk special case — one kernel serves both
    the per-layer and ring-hop gradient paths."""
    kernel = functools.partial(
        _chunk_bwd_fused_kernel, scale=scale, causal=causal,
        seq_len_q=Tq, seq_len_k=Tk, block_q=block_q, block_k=block_k,
        dropout_rate=dropout_rate)
    spec_q = _vmem_spec((None, Tq, D), lambda i, kb: (i, 0, 0))
    spec_kv = _vmem_spec((None, block_k, D), lambda i, kb: (i, kb, 0))
    spec_tl = _vmem_spec((None, Tq, LANES), lambda i, kb: (i, 0, 0))
    kw = {}
    cp = _compiler_params(1, 2)
    if cp is not None:
        kw["compiler_params"] = cp
    return pl.pallas_call(
        kernel,
        grid=(BH, Tk // block_k),
        in_specs=[_smem_spec(), _smem_spec(), spec_q, spec_kv, spec_kv,
                  spec_q, spec_tl, spec_tl],
        out_specs=[spec_q, spec_kv, spec_kv],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), dtype),
        ],
        scratch_shapes=[_scratch((Tq, D))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, offs, qf, kf, vf, gf, lse, delta)


def _flash_bwd(scale, causal, block_q, block_k, dropout_rate, residuals, g):
    q, k, v, seed, o, lse = residuals  # lse: (BH, T) — see _flash_fwd_rule
    B, H, T, D = q.shape
    BH = B * H
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1).reshape(BH, T)
    # stats ride a LANES-wide trailing dim (see module docstring) — but
    # only transiently, materialized here just before the kernels; the
    # per-layer residual that lives across the whole backward pass is the
    # compact (BH, T) form (128x less HBM)
    delta = jnp.broadcast_to(delta[:, :, None], (BH, T, LANES))
    lse = jnp.broadcast_to(lse[:, :, None], (BH, T, LANES))
    qf, kf, vf = (t.reshape(BH, T, D) for t in (q, k, v))
    gf = g.reshape(BH, T, D)

    if T == block_q and T == block_k:
        # single-tile case: one fused launch computes dq, dk, dv together
        dq, dk, dv = _flash_bwd_fused(
            scale, causal, block_q, block_k, dropout_rate,
            seed, qf, kf, vf, gf, lse, delta, BH, T, D, q.dtype)
        shape = (B, H, T, D)
        return (dq.reshape(shape), dk.reshape(shape), dv.reshape(shape),
                None)

    if pltpu is not None and T * D * 4 <= FUSED_DQ_SCRATCH_BYTES:
        # multi-tile but the (T, D) f32 dq scratch fits VMEM: kv-major
        # fully-fused backward — one launch and one p/ds recompute per
        # tile instead of two of each (split kernels below remain for
        # longer resident sequences, and for pure-CPU installs where
        # pltpu — and so VMEM scratch — is unavailable)
        dq, dk, dv = _fused_kv_major_bwd(
            scale, causal, block_q, block_k, dropout_rate,
            seed, jnp.zeros((3,), jnp.int32), qf, kf, vf, gf, lse, delta,
            BH, T, T, D, q.dtype)
        shape = (B, H, T, D)
        return (dq.reshape(shape), dk.reshape(shape), dv.reshape(shape),
                None)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, T // block_q),
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
        ],
        out_specs=_vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret_mode(),
    )(seed, qf, kf, vf, gf, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, T // block_k),
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, LANES), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, LANES), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        interpret=_interpret_mode(),
    )(seed, qf, kf, vf, gf, lse, delta)

    shape = (B, H, T, D)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape), None


# ---------------------------------------------------------------------------
# streamed variant: K/V blocks fetched from HBM per grid step
#
# The resident kernels above hold the full (T, D) K and V for one
# (batch, head) in VMEM, which caps single-chip T at roughly 32k for
# D=64 bf16. These variants add the kv axis to the pallas grid — TPU
# grids iterate sequentially with the last dimension minor, so the
# online-softmax state (acc, m, l) carries across kv steps in VMEM
# scratch while Mosaic double-buffers the (block, D) K/V fetches.
# VMEM use is then O(block^2) regardless of T: the sequence length is
# bounded by HBM only, and ring/Ulysses take over past one chip.
# Fully-masked causal tiles skip their matmuls via pl.when (the block
# fetch still happens; at block>=128 the kernel stays compute-bound).
# ---------------------------------------------------------------------------


def _compiler_params(n_parallel: int, n_total: int):
    """Mark leading grid dims parallel, trailing (carry) dims arbitrary."""
    if pltpu is None:
        return None
    try:
        sem = (("parallel",) * n_parallel
               + ("arbitrary",) * (n_total - n_parallel))
        return pltpu.CompilerParams(dimension_semantics=sem)
    except Exception:  # pragma: no cover — older/newer param spelling
        return None


def _scratch(shape):
    return pltpu.VMEM(shape, jnp.float32) if pltpu is not None else None


def _fwd_kernel_stream(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                       acc_ref, m_ref, l_ref, *, scale, causal, seq_len,
                       block_q, block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kv = seq_len // block_k
    q_first = j * block_q
    k_first = kb * block_k
    last_kb = (((j + 1) * block_q - 1) // block_k) if causal else n_kv - 1

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    needed = (k_first <= q_first + block_q - 1) if causal else kb >= 0

    @pl.when(needed)
    def _update():
        acc, m_new, l_new = _fwd_tile(
            q_ref[...], k_ref[...], v_ref[...],
            acc_ref[...], m_ref[...][:, :1], l_ref[...][:, :1],
            scale=scale, causal=causal, q_first=q_first, k_first=k_first,
            block_q=block_q, block_k=block_k, seed=seed_ref[0], bh=i,
            dropout_rate=dropout_rate)
        acc_ref[...] = acc
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == last_kb)
    def _finalize():
        m = m_ref[...][:, :1]
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[...] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape)


def _flash_fwd_stream(q, k, v, seed, scale, causal, block_q, block_k,
                      dropout_rate):
    B, H, T, D = q.shape
    BH = B * H
    qf, kf, vf = (t.reshape(BH, T, D) for t in (q, k, v))
    grid = (BH, T // block_q, T // block_k)
    kernel = functools.partial(
        _fwd_kernel_stream, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(2, 3)
    if cp is not None:
        kw["compiler_params"] = cp
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j, kb: (i, kb, 0)),
            _vmem_spec((None, block_k, D), lambda i, j, kb: (i, kb, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j, kb: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        ],
        scratch_shapes=[_scratch((block_q, D)), _scratch((block_q, LANES)),
                        _scratch((block_q, LANES))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, qf, kf, vf)
    return o.reshape(B, H, T, D), lse


def _bwd_dq_kernel_stream(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, dq_ref, dq_acc_ref, *, scale, causal,
                          seq_len, block_q, block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kv = seq_len // block_k
    q_first = j * block_q
    k_first = kb * block_k
    last_kb = (((j + 1) * block_q - 1) // block_k) if causal else n_kv - 1

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    needed = (k_first <= q_first + block_q - 1) if causal else kb >= 0

    @pl.when(needed)
    def _update():
        dq_acc_ref[...] = dq_acc_ref[...] + _dq_tile(
            q_ref[...], k_ref[...], v_ref[...], do_ref[...],
            lse_ref[...][:, :1], delta_ref[...][:, :1], scale=scale,
            causal=causal, q_first=q_first, k_first=k_first,
            block_q=block_q, block_k=block_k, seed=seed_ref[0], bh=i,
            dropout_rate=dropout_rate)

    @pl.when(kb == last_kb)
    def _finalize():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_stream(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                           delta_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                           *, scale, causal, seq_len, block_q, block_k,
                           dropout_rate):
    i = pl.program_id(0)
    kb = pl.program_id(1)
    jb = pl.program_id(2)
    n_q = seq_len // block_q
    k_first = kb * block_k
    q_first = jb * block_q

    @pl.when(jb == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    needed = (q_first + block_q - 1 >= k_first) if causal else jb >= 0

    @pl.when(needed)
    def _update():
        dk_c, dv_c, _ = _dkv_tile(
            q_ref[...], k_ref[...], v_ref[...], do_ref[...],
            lse_ref[...][:, :1], delta_ref[...][:, :1], scale=scale,
            causal=causal, q_first=q_first, k_first=k_first,
            block_q=block_q, block_k=block_k, seed=seed_ref[0], bh=i,
            dropout_rate=dropout_rate)
        dk_acc_ref[...] = dk_acc_ref[...] + dk_c
        dv_acc_ref[...] = dv_acc_ref[...] + dv_c

    @pl.when(jb == n_q - 1)
    def _finalize():
        dk_ref[...] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd_stream(scale, causal, block_q, block_k, dropout_rate,
                      residuals, g):
    q, k, v, seed, o, lse = residuals  # lse: (BH, T)
    B, H, T, D = q.shape
    BH = B * H
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1).reshape(BH, T)
    delta = jnp.broadcast_to(delta[:, :, None], (BH, T, LANES))
    lse = jnp.broadcast_to(lse[:, :, None], (BH, T, LANES))
    qf, kf, vf = (t.reshape(BH, T, D) for t in (q, k, v))
    gf = g.reshape(BH, T, D)
    kw = {}
    cp = _compiler_params(2, 3)
    if cp is not None:
        kw["compiler_params"] = cp

    dq_kernel = functools.partial(
        _bwd_dq_kernel_stream, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, T // block_q, T // block_k),
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j, kb: (i, kb, 0)),
            _vmem_spec((None, block_k, D), lambda i, j, kb: (i, kb, 0)),
            _vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j, kb: (i, j, 0)),
        ],
        out_specs=_vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[_scratch((block_q, D))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, qf, kf, vf, gf, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel_stream, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, T // block_k, T // block_q),
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, kb, jb: (i, jb, 0)),
            _vmem_spec((None, block_k, D), lambda i, kb, jb: (i, kb, 0)),
            _vmem_spec((None, block_k, D), lambda i, kb, jb: (i, kb, 0)),
            _vmem_spec((None, block_q, D), lambda i, kb, jb: (i, jb, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, kb, jb: (i, jb, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, kb, jb: (i, jb, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_k, D), lambda i, kb, jb: (i, kb, 0)),
            _vmem_spec((None, block_k, D), lambda i, kb, jb: (i, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        scratch_shapes=[_scratch((block_k, D)), _scratch((block_k, D))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, qf, kf, vf, gf, lse, delta)

    shape = (B, H, T, D)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape), None


# --- triangular causal grid (scalar-prefetched tile map) -------------------
#
# The rectangular (BH, n_q, n_kv) streamed grid runs — and fetches K/V
# for — every tile, including the ~half that causal masking discards
# (pl.when skips their matmuls, not their copies). For causal with
# block_q == block_k the grid is flattened to just the lower-triangle
# tiles: a host-precomputed (2, M) int32 tile map (M = n(n+1)/2) rides
# scalar prefetch into SMEM, and the BlockSpec index maps read the
# (q-block, kv-block) coordinates from it per grid step. Tiles of one
# q-row stay adjacent, so the output block and the online-softmax
# scratch carry across kv steps exactly as in the rectangular grid.


def _tri_tile_map(n: int, kv_major: bool) -> np.ndarray:
    """(2, M) int32: row 0 = outer block index, row 1 = inner (carried)
    block index. q-major (fwd/dq): for each q-block j, kv 0..j.
    kv-major (dkv): for each kv-block kb, q kb..n-1."""
    if kv_major:
        pairs = [(kb, jb) for kb in range(n) for jb in range(kb, n)]
    else:
        pairs = [(j, kb) for j in range(n) for kb in range(j + 1)]
    return np.asarray(pairs, np.int32).T.copy()


def _fwd_kernel_tri(tmap_ref, seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                    acc_ref, m_ref, l_ref, *, scale, block, dropout_rate):
    i = pl.program_id(0)
    t = pl.program_id(1)
    j = tmap_ref[0, t]
    kb = tmap_ref[1, t]
    q_first = j * block
    k_first = kb * block

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    acc, m_new, l_new = _fwd_tile(
        q_ref[...], k_ref[...], v_ref[...],
        acc_ref[...], m_ref[...][:, :1], l_ref[...][:, :1], scale=scale,
        causal=True, q_first=q_first, k_first=k_first, block_q=block,
        block_k=block, seed=seed_ref[0], bh=i, dropout_rate=dropout_rate)
    acc_ref[...] = acc
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == j)
    def _finalize():
        mf = m_ref[...][:, :1]
        lf = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[...] = (acc_ref[...] / lf).astype(o_ref.dtype)
        lse_ref[...] = jnp.broadcast_to(mf + jnp.log(lf), lse_ref.shape)


def _flash_fwd_tri(q, k, v, seed, scale, block, dropout_rate):
    B, H, T, D = q.shape
    BH = B * H
    n = T // block
    tmap = jnp.asarray(_tri_tile_map(n, kv_major=False))
    qf, kf, vf = (t.reshape(BH, T, D) for t in (q, k, v))
    kernel = functools.partial(_fwd_kernel_tri, scale=scale, block=block,
                               dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(1, 2)
    if cp is not None:
        kw["compiler_params"] = cp
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, tmap.shape[1]),
        in_specs=[
            _vmem_spec((None, block, D), lambda i, t, tm, sd: (i, tm[0, t], 0)),
            _vmem_spec((None, block, D), lambda i, t, tm, sd: (i, tm[1, t], 0)),
            _vmem_spec((None, block, D), lambda i, t, tm, sd: (i, tm[1, t], 0)),
        ],
        out_specs=[
            _vmem_spec((None, block, D), lambda i, t, tm, sd: (i, tm[0, t], 0)),
            _vmem_spec((None, block, LANES),
                       lambda i, t, tm, sd: (i, tm[0, t], 0)),
        ],
        scratch_shapes=[_scratch((block, D)), _scratch((block, LANES)),
                        _scratch((block, LANES))],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        ],
        interpret=_interpret_mode(),
        **kw,
    )(tmap, seed, qf, kf, vf)
    return o.reshape(B, H, T, D), lse


def _bwd_dq_kernel_tri(tmap_ref, seed_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dq_ref, dq_acc_ref, *, scale,
                       block, dropout_rate):
    i = pl.program_id(0)
    t = pl.program_id(1)
    j = tmap_ref[0, t]
    kb = tmap_ref[1, t]
    q_first = j * block
    k_first = kb * block

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    dq_acc_ref[...] = dq_acc_ref[...] + _dq_tile(
        q_ref[...], k_ref[...], v_ref[...], do_ref[...],
        lse_ref[...][:, :1], delta_ref[...][:, :1], scale=scale,
        causal=True, q_first=q_first, k_first=k_first, block_q=block,
        block_k=block, seed=seed_ref[0], bh=i, dropout_rate=dropout_rate)

    @pl.when(kb == j)
    def _finalize():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_tri(tmap_ref, seed_ref, q_ref, k_ref, v_ref, do_ref,
                        lse_ref, delta_ref, dk_ref, dv_ref, dk_acc_ref,
                        dv_acc_ref, *, scale, block, n_q, dropout_rate):
    i = pl.program_id(0)
    t = pl.program_id(1)
    kb = tmap_ref[0, t]
    jb = tmap_ref[1, t]
    k_first = kb * block
    q_first = jb * block

    @pl.when(jb == kb)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    dk_c, dv_c, _ = _dkv_tile(
        q_ref[...], k_ref[...], v_ref[...], do_ref[...],
        lse_ref[...][:, :1], delta_ref[...][:, :1], scale=scale,
        causal=True, q_first=q_first, k_first=k_first, block_q=block,
        block_k=block, seed=seed_ref[0], bh=i, dropout_rate=dropout_rate)
    dk_acc_ref[...] = dk_acc_ref[...] + dk_c
    dv_acc_ref[...] = dv_acc_ref[...] + dv_c

    @pl.when(jb == n_q - 1)
    def _finalize():
        dk_ref[...] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd_tri(scale, block, dropout_rate, residuals, g):
    q, k, v, seed, o, lse = residuals  # lse: (BH, T)
    B, H, T, D = q.shape
    BH = B * H
    n = T // block
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1).reshape(BH, T)
    delta = jnp.broadcast_to(delta[:, :, None], (BH, T, LANES))
    lse = jnp.broadcast_to(lse[:, :, None], (BH, T, LANES))
    qf, kf, vf = (t.reshape(BH, T, D) for t in (q, k, v))
    gf = g.reshape(BH, T, D)
    kw = {}
    cp = _compiler_params(1, 2)
    if cp is not None:
        kw["compiler_params"] = cp

    tmap_q = jnp.asarray(_tri_tile_map(n, kv_major=False))
    dq_kernel = functools.partial(_bwd_dq_kernel_tri, scale=scale,
                                  block=block, dropout_rate=dropout_rate)
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, tmap_q.shape[1]),
            in_specs=[
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[0, t], 0)),
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[1, t], 0)),
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[1, t], 0)),
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[0, t], 0)),
                _vmem_spec((None, block, LANES),
                           lambda i, t, tm, sd: (i, tm[0, t], 0)),
                _vmem_spec((None, block, LANES),
                           lambda i, t, tm, sd: (i, tm[0, t], 0)),
            ],
            out_specs=_vmem_spec((None, block, D),
                                 lambda i, t, tm, sd: (i, tm[0, t], 0)),
            scratch_shapes=[_scratch((block, D))],
        ),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret_mode(),
        **kw,
    )(tmap_q, seed, qf, kf, vf, gf, lse, delta)

    tmap_kv = jnp.asarray(_tri_tile_map(n, kv_major=True))
    dkv_kernel = functools.partial(_bwd_dkv_kernel_tri, scale=scale,
                                   block=block, n_q=n,
                                   dropout_rate=dropout_rate)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, tmap_kv.shape[1]),
            in_specs=[
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[1, t], 0)),
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[0, t], 0)),
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[0, t], 0)),
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[1, t], 0)),
                _vmem_spec((None, block, LANES),
                           lambda i, t, tm, sd: (i, tm[1, t], 0)),
                _vmem_spec((None, block, LANES),
                           lambda i, t, tm, sd: (i, tm[1, t], 0)),
            ],
            out_specs=[
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[0, t], 0)),
                _vmem_spec((None, block, D),
                           lambda i, t, tm, sd: (i, tm[0, t], 0)),
            ],
            scratch_shapes=[_scratch((block, D)), _scratch((block, D))],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        interpret=_interpret_mode(),
        **kw,
    )(tmap_kv, seed, qf, kf, vf, gf, lse, delta)

    shape = (B, H, T, D)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape), None


def _tri_eligible(causal, block_q, block_k):
    return causal and block_q == block_k


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_stream(q, k, v, seed, scale, causal, block_q, block_k,
                  dropout_rate):
    if _tri_eligible(causal, block_q, block_k):
        o, _ = _flash_fwd_tri(q, k, v, seed, scale, block_q, dropout_rate)
    else:
        o, _ = _flash_fwd_stream(q, k, v, seed, scale, causal, block_q,
                                 block_k, dropout_rate)
    return o


def _flash_stream_fwd_rule(q, k, v, seed, scale, causal, block_q, block_k,
                           dropout_rate):
    if _tri_eligible(causal, block_q, block_k):
        o, lse = _flash_fwd_tri(q, k, v, seed, scale, block_q, dropout_rate)
    else:
        o, lse = _flash_fwd_stream(q, k, v, seed, scale, causal, block_q,
                                   block_k, dropout_rate)
    return o, (q, k, v, seed, o, lse[..., 0])  # compact (BH, T) residual


def _flash_stream_bwd_rule(scale, causal, block_q, block_k, dropout_rate,
                           residuals, g):
    if _tri_eligible(causal, block_q, block_k):
        return _flash_bwd_tri(scale, block_q, dropout_rate, residuals, g)
    return _flash_bwd_stream(scale, causal, block_q, block_k, dropout_rate,
                             residuals, g)


_flash_stream.defvjp(_flash_stream_fwd_rule, _flash_stream_bwd_rule)


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

_INTERPRET = False


def _interpret_mode() -> bool:
    return _INTERPRET or jax.default_backend() != "tpu"


def set_interpret(flag: bool) -> None:
    """Force interpreter mode (CPU testing)."""
    global _INTERPRET
    _INTERPRET = flag


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, seed, scale, causal, block_q, block_k, dropout_rate):
    o, _ = _flash_fwd(q, k, v, seed, scale, causal, block_q, block_k,
                      dropout_rate)
    return o


def _flash_fwd_rule(q, k, v, seed, scale, causal, block_q, block_k,
                    dropout_rate):
    o, lse = _flash_fwd(q, k, v, seed, scale, causal, block_q, block_k,
                        dropout_rate)
    # keep the residual compact: the kernel emits lse LANES-broadcast
    # ((BH,T,LANES), a Mosaic tiling requirement), but storing that per
    # layer until the backward pass wastes 128x the HBM — save (BH, T)
    # and rebroadcast in _flash_bwd
    return o, (q, k, v, seed, o, lse[..., 0])


def _flash_bwd_rule(scale, causal, block_q, block_k, dropout_rate,
                    residuals, g):
    return _flash_bwd(scale, causal, block_q, block_k, dropout_rate,
                      residuals, g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _auto_block(T: int) -> int:
    """Largest tile size in {512, 256, 128} dividing T. 512x512 tiles
    measured 18.2 TF/s fwd+bwd vs 7.9 at 128x128 on v5e (T=1024, D=64) —
    bigger tiles amortize the kv fori_loop and feed the MXU longer
    contractions; past 512 returns flatten (1024 measured 17.5)."""
    for b in (512, 256, 128):
        if T % b == 0:
            return b
    return BLOCK


# ---------------------------------------------------------------------------
# chunk attention: (o, lse) with global-position offsets — the ring
# attention hop core (parallel/ring_attention.py)
#
# One (q-chunk, kv-chunk) block attention where q holds global positions
# [q_offset, q_offset+Tq) and k/v [k_offset, k_offset+Tk). Returns the
# chunk-local softmax output AND its logsumexp, both differentiable, so
# callers can merge chunks with the online-softmax recurrence in plain
# JAX (the VJP of the merge needs d(lse), hence the custom rule below).
#
# Backward trick: for o = softmax(s) @ v and lse = logsumexp(s),
# upstream (do, dlse) gives ds = p * (dot(do, v) - delta + dlse) with
# delta = rowsum(do * o) — i.e. exactly the standard flash backward with
# delta replaced by (delta - dlse). The existing dq/dkv kernels are
# reused unmodified with that substitution.
# ---------------------------------------------------------------------------


def _flash_prologue(D, scale, dropout_rate, dropout_rng):
    """Shared entry prologue: head-dim scale default, dropout validation,
    and the in-kernel seed derivation — one source of truth for every
    flash entry point (single-chip attention and the ring chunk op)."""
    if scale is None:
        scale = D ** -0.5
    rate = float(dropout_rate)
    if rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    if dropout_rng is not None and rate > 0.0:
        seed = jax.random.randint(dropout_rng, (1,), 0, 2**31 - 1,
                                  dtype=jnp.int32)
    else:
        rate = 0.0
        seed = jnp.zeros((1,), jnp.int32)
    return float(scale), rate, seed


def _block_for(T, override):
    b = min(override if override is not None else _auto_block(T), T)
    assert T % b == 0, (T, b)
    return b


def pallas_flash_chunk(q, k, v, *, scale=None, causal=True,
                       q_offset=0, k_offset=0,
                       block_q=None, block_k=None,
                       dropout_rate: float = 0.0,
                       dropout_rng=None, bh_offset=0):
    """Chunk attention with stats: returns (o, lse).

    q: (B, H, Tq, D); k, v: (B, H, Tk, D). Causal masking compares
    global positions (q_offset + row) >= (k_offset + col); the offsets
    may be Python ints or traced int32 scalars (e.g. derived from
    ``jax.lax.axis_index`` in a ring), so one compiled kernel serves
    every hop. lse is (B, H, Tq) float32 (logsumexp over this chunk's
    keys only; -inf rows are possible when causal masks an entire row —
    callers merging chunks handle that in the recurrence).
    Differentiable in q, k, v including through lse. ``bh_offset``
    decorrelates the in-kernel dropout stream when the (batch, head)
    dims are themselves shards of a larger array.
    """
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale, rate, seed = _flash_prologue(D, scale, dropout_rate, dropout_rng)
    block_q = _block_for(Tq, block_q)
    block_k = _block_for(Tk, block_k)
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32),
                      jnp.asarray(bh_offset, jnp.int32)])
    o, lse = _flash_chunk(q, k, v, seed, offs, scale, bool(causal),
                          block_q, block_k, rate)
    return o, lse


def _chunk_fwd_kernel(seed_ref, off_ref, q_ref, k_ref, v_ref, o_ref,
                      lse_ref, *, scale, causal, seq_len_k, block_q,
                      block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[...]
    D = q.shape[-1]
    q_first = off_ref[0] + j * block_q
    n_kv = seq_len_k // block_k
    if causal:
        # skip fully-masked kv tiles: tile kb contributes iff its first
        # key position <= this q block's last position (dynamic bound —
        # the offsets live in SMEM). Negative/zero bounds make the loop
        # a no-op (fully masked hop; lse stays -inf).
        n_kv = jnp.clip(
            (q_first + block_q - 1 - off_ref[1]) // block_k + 1, 0, n_kv)

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        return _fwd_tile(q, k, v, acc, m, l, scale=scale, causal=causal,
                         q_first=q_first,
                         k_first=off_ref[1] + kb * block_k,
                         block_q=block_q, block_k=block_k,
                         seed=seed_ref[0], bh=off_ref[2] + i,
                         dropout_rate=dropout_rate)

    acc = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kv, body, (acc, m0, l0))
    lse = jnp.where(l > 0.0, m + jnp.log(jnp.maximum(l, 1e-30)),
                    NEG_INF)
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to(lse, (block_q, LANES))


def _chunk_bwd_dq_kernel(seed_ref, off_ref, q_ref, k_ref, v_ref, do_ref,
                         lse_ref, deltap_ref, dq_ref, *, scale, causal,
                         seq_len_k, block_q, block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...][:, :1]
    deltap = deltap_ref[...][:, :1]
    q_first = off_ref[0] + j * block_q
    n_kv = seq_len_k // block_k
    if causal:
        n_kv = jnp.clip(
            (q_first + block_q - 1 - off_ref[1]) // block_k + 1, 0, n_kv)

    def body(kb, dq):
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        return dq + _dq_tile(q, k, v, do, lse, deltap, scale=scale,
                             causal=causal, q_first=q_first,
                             k_first=off_ref[1] + kb * block_k,
                             block_q=block_q, block_k=block_k,
                             seed=seed_ref[0], bh=off_ref[2] + i,
                             dropout_rate=dropout_rate)

    dq_ref[...] = jax.lax.fori_loop(
        0, n_kv, body, jnp.zeros(q.shape, jnp.float32)).astype(dq_ref.dtype)


def _chunk_bwd_dkv_kernel(seed_ref, off_ref, q_ref, k_ref, v_ref, do_ref,
                          lse_ref, deltap_ref, dk_ref, dv_ref, *, scale,
                          causal, seq_len_q, block_q, block_k,
                          dropout_rate):
    i = pl.program_id(0)
    kb = pl.program_id(1)
    k = k_ref[...]
    v = v_ref[...]
    k_first = off_ref[1] + kb * block_k
    n_q = seq_len_q // block_q
    if causal:
        # first q tile whose last row can see this kv tile's first key
        jb0 = jnp.clip((k_first - off_ref[0]) // block_q, 0, n_q)
    else:
        jb0 = 0

    def body(jb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(jb * block_q, block_q), :]
        do = do_ref[pl.ds(jb * block_q, block_q), :]
        lse = lse_ref[pl.ds(jb * block_q, block_q), :][:, :1]
        deltap = deltap_ref[pl.ds(jb * block_q, block_q), :][:, :1]
        dk_c, dv_c, _ = _dkv_tile(q, k, v, do, lse, deltap, scale=scale,
                               causal=causal,
                               q_first=off_ref[0] + jb * block_q,
                               k_first=k_first,
                               block_q=block_q, block_k=block_k,
                               seed=seed_ref[0], bh=off_ref[2] + i,
                               dropout_rate=dropout_rate)
        return dk + dk_c, dv + dv_c

    dk0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(jb0, n_q, body, (dk0, jnp.zeros_like(dk0)))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


# --- streamed chunk kernels: ring hops past the resident K/V bound ---------
#
# The resident chunk kernels above hold one (batch, head)'s full (Tk, D)
# K/V (fwd, dq) or (Tq, D) q-side arrays (dkv) in VMEM, so ring hops
# were bounded by STREAM_KV_BYTES per device shard — exactly the
# long-per-shard runs ring attention exists for fell back to the
# q-chunked einsum body (round-3 verdict). These variants put the
# streamed axis on the pallas grid with online state in VMEM scratch —
# the same transformation the single-chip streamed family applies to
# the resident family — while keeping the chunk op's contract: global
# positions from the SMEM offsets vector (so one compiled kernel serves
# every hop), (o, lse) outputs, -inf lse on fully-masked rows, and the
# shared tile math (bit-identical numerics incl. the dropout stream).
# Causality with dynamic offsets: tiles skip via pl.when on global
# positions; the finalize index is the clipped last contributing kv
# tile (clip to 0 makes fully-masked q rows finalize on an untouched
# accumulator -> o = 0, lse = -inf, as in the resident kernel).


def _chunk_fwd_kernel_stream(seed_ref, off_ref, q_ref, k_ref, v_ref, o_ref,
                             lse_ref, acc_ref, m_ref, l_ref, *, scale,
                             causal, seq_len_k, block_q, block_k,
                             dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kv = seq_len_k // block_k
    q_first = off_ref[0] + j * block_q
    k_first = off_ref[1] + kb * block_k
    if causal:
        last_kb = jnp.clip((q_first + block_q - 1 - off_ref[1]) // block_k,
                           0, n_kv - 1)
        needed = k_first <= q_first + block_q - 1
    else:
        last_kb = n_kv - 1
        needed = kb >= 0

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(needed)
    def _update():
        acc, m_new, l_new = _fwd_tile(
            q_ref[...], k_ref[...], v_ref[...],
            acc_ref[...], m_ref[...][:, :1], l_ref[...][:, :1],
            scale=scale, causal=causal, q_first=q_first, k_first=k_first,
            block_q=block_q, block_k=block_k, seed=seed_ref[0],
            bh=off_ref[2] + i, dropout_rate=dropout_rate)
        acc_ref[...] = acc
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == last_kb)
    def _finalize():
        m = m_ref[...][:, :1]
        l = l_ref[...][:, :1]
        lse = jnp.where(l > 0.0, m + jnp.log(jnp.maximum(l, 1e-30)),
                        NEG_INF)
        o_ref[...] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _chunk_bwd_dq_kernel_stream(seed_ref, off_ref, q_ref, k_ref, v_ref,
                                do_ref, lse_ref, deltap_ref, dq_ref,
                                dq_acc_ref, *, scale, causal, seq_len_k,
                                block_q, block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kb = pl.program_id(2)
    n_kv = seq_len_k // block_k
    q_first = off_ref[0] + j * block_q
    k_first = off_ref[1] + kb * block_k
    if causal:
        last_kb = jnp.clip((q_first + block_q - 1 - off_ref[1]) // block_k,
                           0, n_kv - 1)
        needed = k_first <= q_first + block_q - 1
    else:
        last_kb = n_kv - 1
        needed = kb >= 0

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    @pl.when(needed)
    def _update():
        dq_acc_ref[...] = dq_acc_ref[...] + _dq_tile(
            q_ref[...], k_ref[...], v_ref[...], do_ref[...],
            lse_ref[...][:, :1], deltap_ref[...][:, :1], scale=scale,
            causal=causal, q_first=q_first, k_first=k_first,
            block_q=block_q, block_k=block_k, seed=seed_ref[0],
            bh=off_ref[2] + i, dropout_rate=dropout_rate)

    @pl.when(kb == last_kb)
    def _finalize():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _chunk_bwd_dkv_kernel_stream(seed_ref, off_ref, q_ref, k_ref, v_ref,
                                 do_ref, lse_ref, deltap_ref, dk_ref,
                                 dv_ref, dk_acc_ref, dv_acc_ref, *, scale,
                                 causal, seq_len_q, block_q, block_k,
                                 dropout_rate):
    i = pl.program_id(0)
    kb = pl.program_id(1)
    jb = pl.program_id(2)
    n_q = seq_len_q // block_q
    k_first = off_ref[1] + kb * block_k
    q_first = off_ref[0] + jb * block_q
    needed = (q_first + block_q - 1 >= k_first) if causal else jb >= 0

    @pl.when(jb == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    @pl.when(needed)
    def _update():
        dk_c, dv_c, _ = _dkv_tile(
            q_ref[...], k_ref[...], v_ref[...], do_ref[...],
            lse_ref[...][:, :1], deltap_ref[...][:, :1], scale=scale,
            causal=causal, q_first=q_first, k_first=k_first,
            block_q=block_q, block_k=block_k, seed=seed_ref[0],
            bh=off_ref[2] + i, dropout_rate=dropout_rate)
        dk_acc_ref[...] = dk_acc_ref[...] + dk_c
        dv_acc_ref[...] = dv_acc_ref[...] + dv_c

    @pl.when(jb == n_q - 1)
    def _finalize():
        dk_ref[...] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _chunk_streaming(Tq, Tk, D, itemsize) -> bool:
    """Route a chunk call to the streamed kernels when either side's
    resident arrays (K/V for fwd/dq, q-side for dkv) exceed the measured
    resident-compile bound. pltpu-less installs keep the resident
    kernels at any size (their scratch-free fori_loop bodies need no
    TPU memory spaces), mirroring pallas_flash_attention's degrade."""
    if pltpu is None:
        return False
    return _should_stream(max(Tq, Tk), D, itemsize)


def _chunk_fwd_stream(q, k, v, seed, offs, scale, causal, block_q, block_k,
                      dropout_rate):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    qf = q.reshape(BH, Tq, D)
    kf = k.reshape(BH, Tk, D)
    vf = v.reshape(BH, Tk, D)
    kernel = functools.partial(
        _chunk_fwd_kernel_stream, scale=scale, causal=causal, seq_len_k=Tk,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(2, 3)
    if cp is not None:
        kw["compiler_params"] = cp
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, Tq // block_q, Tk // block_k),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j, kb: (i, kb, 0)),
            _vmem_spec((None, block_k, D), lambda i, j, kb: (i, kb, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j, kb: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Tq, LANES), jnp.float32),
        ],
        scratch_shapes=[_scratch((block_q, D)), _scratch((block_q, LANES)),
                        _scratch((block_q, LANES))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, offs, qf, kf, vf)
    return o.reshape(B, H, Tq, D), lse[..., 0].reshape(B, H, Tq)


def _chunk_bwd_stream(scale, causal, block_q, block_k, dropout_rate,
                      seed, offs, qf, kf, vf, gf, lse_b, deltap,
                      BH, Tq, Tk, D, dtype):
    kw = {}
    cp = _compiler_params(2, 3)
    if cp is not None:
        kw["compiler_params"] = cp
    dq = pl.pallas_call(
        functools.partial(
            _chunk_bwd_dq_kernel_stream, scale=scale, causal=causal,
            seq_len_k=Tk, block_q=block_q, block_k=block_k,
            dropout_rate=dropout_rate),
        grid=(BH, Tq // block_q, Tk // block_k),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j, kb: (i, kb, 0)),
            _vmem_spec((None, block_k, D), lambda i, j, kb: (i, kb, 0)),
            _vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j, kb: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j, kb: (i, j, 0)),
        ],
        out_specs=_vmem_spec((None, block_q, D), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), dtype),
        scratch_shapes=[_scratch((block_q, D))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, offs, qf, kf, vf, gf, lse_b, deltap)

    dk, dv = pl.pallas_call(
        functools.partial(
            _chunk_bwd_dkv_kernel_stream, scale=scale, causal=causal,
            seq_len_q=Tq, block_q=block_q, block_k=block_k,
            dropout_rate=dropout_rate),
        grid=(BH, Tk // block_k, Tq // block_q),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, kb, jb: (i, jb, 0)),
            _vmem_spec((None, block_k, D), lambda i, kb, jb: (i, kb, 0)),
            _vmem_spec((None, block_k, D), lambda i, kb, jb: (i, kb, 0)),
            _vmem_spec((None, block_q, D), lambda i, kb, jb: (i, jb, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, kb, jb: (i, jb, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, kb, jb: (i, jb, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_k, D), lambda i, kb, jb: (i, kb, 0)),
            _vmem_spec((None, block_k, D), lambda i, kb, jb: (i, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), dtype),
        ],
        scratch_shapes=[_scratch((block_k, D)), _scratch((block_k, D))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, offs, qf, kf, vf, gf, lse_b, deltap)
    return dq, dk, dv


def _chunk_fwd(q, k, v, seed, offs, scale, causal, block_q, block_k,
               dropout_rate):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if _chunk_streaming(Tq, Tk, D, jnp.dtype(q.dtype).itemsize):
        return _chunk_fwd_stream(q, k, v, seed, offs, scale, causal,
                                 block_q, block_k, dropout_rate)
    BH = B * H
    qf = q.reshape(BH, Tq, D)
    kf = k.reshape(BH, Tk, D)
    vf = v.reshape(BH, Tk, D)
    kernel = functools.partial(
        _chunk_fwd_kernel, scale=scale, causal=causal, seq_len_k=Tk,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, Tq // block_q),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, Tk, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, Tk, D), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Tq, LANES), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(seed, offs, qf, kf, vf)
    return o.reshape(B, H, Tq, D), lse[..., 0].reshape(B, H, Tq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_chunk(q, k, v, seed, offs, scale, causal, block_q, block_k,
                 dropout_rate):
    return _chunk_fwd(q, k, v, seed, offs, scale, causal, block_q, block_k,
                      dropout_rate)


def _flash_chunk_fwd_rule(q, k, v, seed, offs, scale, causal, block_q,
                          block_k, dropout_rate):
    o, lse = _chunk_fwd(q, k, v, seed, offs, scale, causal, block_q,
                        block_k, dropout_rate)
    return (o, lse), (q, k, v, seed, offs, o, lse)


def _chunk_bwd_fused_kernel(seed_ref, off_ref, q_ref, k_ref, v_ref, do_ref,
                            lse_ref, deltap_ref, dq_ref, dk_ref, dv_ref,
                            dq_acc_ref, *, scale, causal, seq_len_q,
                            seq_len_k, block_q, block_k, dropout_rate):
    """kv-major fully-fused chunk backward (the ring-hop gradient path;
    also serves the resident multi-tile path via _fused_kv_major_bwd with
    zero offsets): dq accumulates in a (Tq, D) f32 VMEM scratch across
    the sequential grid, dk/dv write per kv block, and every tile's p/ds
    recompute (through _dkv_tile, the shared math) serves all three
    gradients. Global-position causal skip identical to
    _chunk_bwd_dkv_kernel."""
    i = pl.program_id(0)
    kb = pl.program_id(1)
    n_kv = seq_len_k // block_k
    k = k_ref[...]
    v = v_ref[...]
    k_first = off_ref[1] + kb * block_k
    n_q = seq_len_q // block_q
    if causal:
        jb0 = jnp.clip((k_first - off_ref[0]) // block_q, 0, n_q)
    else:
        jb0 = 0

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    def body(jb, carry):
        dk, dv = carry
        q_first = jb * block_q
        q = q_ref[pl.ds(q_first, block_q), :]
        do = do_ref[pl.ds(q_first, block_q), :]
        lse = lse_ref[pl.ds(q_first, block_q), :][:, :1]
        deltap = deltap_ref[pl.ds(q_first, block_q), :][:, :1]
        dk_c, dv_c, dsc = _dkv_tile(q, k, v, do, lse, deltap, scale=scale,
                                    causal=causal,
                                    q_first=off_ref[0] + q_first,
                                    k_first=k_first, block_q=block_q,
                                    block_k=block_k, seed=seed_ref[0],
                                    bh=off_ref[2] + i,
                                    dropout_rate=dropout_rate)
        dq_c = jax.lax.dot_general(dsc, k, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        dq_acc_ref[pl.ds(q_first, block_q), :] = (
            dq_acc_ref[pl.ds(q_first, block_q), :] + dq_c)
        return dk + dk_c, dv + dv_c

    dk0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(jb0, n_q, body, (dk0, jnp.zeros_like(dk0)))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)

    @pl.when(kb == n_kv - 1)
    def _finalize():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _flash_chunk_bwd_rule(scale, causal, block_q, block_k, dropout_rate,
                          residuals, g):
    q, k, v, seed, offs, o, lse = residuals
    do, dlse = g
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    # delta' = rowsum(do * o) - dlse: folds the lse cotangent into the
    # standard flash backward (ds = p * (dp - delta'))
    deltap = (jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                      axis=-1) - dlse.astype(jnp.float32)).reshape(BH, Tq)
    # rows fully masked in this chunk have lse = -inf and p = exp(s - lse)
    # would be inf * 0; clamp lse for the recompute (p rows are all-masked
    # anyway, so any finite value yields p = exp(NEG_INF - c) = 0)
    lse_c = jnp.maximum(lse, NEG_INF / 2).reshape(BH, Tq)
    deltap = jnp.broadcast_to(deltap[:, :, None], (BH, Tq, LANES))
    lse_b = jnp.broadcast_to(lse_c[:, :, None], (BH, Tq, LANES))
    qf = q.reshape(BH, Tq, D)
    kf = k.reshape(BH, Tk, D)
    vf = v.reshape(BH, Tk, D)
    gf = do.reshape(BH, Tq, D)

    if _chunk_streaming(Tq, Tk, D, jnp.dtype(q.dtype).itemsize):
        dq, dk, dv = _chunk_bwd_stream(
            scale, causal, block_q, block_k, dropout_rate,
            seed, offs, qf, kf, vf, gf, lse_b, deltap,
            BH, Tq, Tk, D, q.dtype)
        return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
                dv.reshape(B, H, Tk, D), None, None)

    if pltpu is not None and Tq * D * 4 <= FUSED_DQ_SCRATCH_BYTES:
        # one fused kv-major launch (see _chunk_bwd_fused_kernel); the
        # split kernels below remain for long chunks and pltpu-less runs
        dq, dk, dv = _fused_kv_major_bwd(
            scale, causal, block_q, block_k, dropout_rate,
            seed, offs, qf, kf, vf, gf, lse_b, deltap,
            BH, Tq, Tk, D, q.dtype)
        shape_q = (B, H, Tq, D)
        shape_k = (B, H, Tk, D)
        return (dq.reshape(shape_q), dk.reshape(shape_k),
                dv.reshape(shape_k), None, None)

    dq = pl.pallas_call(
        functools.partial(
            _chunk_bwd_dq_kernel, scale=scale, causal=causal, seq_len_k=Tk,
            block_q=block_q, block_k=block_k, dropout_rate=dropout_rate),
        grid=(BH, Tq // block_q),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, Tk, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, Tk, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
        ],
        out_specs=_vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
        interpret=_interpret_mode(),
    )(seed, offs, qf, kf, vf, gf, lse_b, deltap)

    dk, dv = pl.pallas_call(
        functools.partial(
            _chunk_bwd_dkv_kernel, scale=scale, causal=causal, seq_len_q=Tq,
            block_q=block_q, block_k=block_k, dropout_rate=dropout_rate),
        grid=(BH, Tk // block_k),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _vmem_spec((None, Tq, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, Tq, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, Tq, LANES), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, Tq, LANES), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), q.dtype),
        ],
        interpret=_interpret_mode(),
    )(seed, offs, qf, kf, vf, gf, lse_b, deltap)

    shape_q = (B, H, Tq, D)
    shape_k = (B, H, Tk, D)
    return (dq.reshape(shape_q), dk.reshape(shape_k), dv.reshape(shape_k),
            None, None)


_flash_chunk.defvjp(_flash_chunk_fwd_rule, _flash_chunk_bwd_rule)


# above this many K+V bytes per (batch, head), stream K/V blockwise
# instead of holding them resident in VMEM. Measured on v5e (D=64 bf16,
# fwd+bwd): resident wins while it compiles (59 ms vs tri-stream 75 at
# T=8192; 102 vs 122 at T=16384 = 4 MiB K+V) and fails Mosaic
# allocation from T=32768 (8 MiB); past the threshold the triangular
# stream carries on at 12.1 TF/s (T=32k) to 18.2 TF/s (T=64k) with
# VMEM use independent of T.
STREAM_KV_BYTES = 4 * 1024 * 1024


def _should_stream(T: int, D: int, itemsize: int) -> bool:
    return 2 * T * D * itemsize > STREAM_KV_BYTES


def pallas_flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                           scale: Optional[float] = None,
                           causal: bool = True,
                           block_q: Optional[int] = None,
                           block_k: Optional[int] = None,
                           dropout_rate: float = 0.0,
                           dropout_rng: Optional[jax.Array] = None,
                           stream: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention. q,k,v: (B, H, T, D); T must be a multiple of the
    block sizes (callers pad or fall back to the einsum path otherwise).

    ``dropout_rate`` > 0 (with ``dropout_rng``) applies inverted dropout to
    the normalized attention weights inside the kernel — the capability the
    dense path gets from _softmax_dropout (GPT1.py:117 semantics) without
    materializing the (T, T) weight matrix. The mask derives from a
    counter-based hash of (seed, head, absolute q-pos, absolute k-pos), so
    the backward kernels — and both kernel variants — regenerate it exactly.

    ``stream`` selects the K/V-streaming grid (VMEM use independent of T;
    sequence length bounded by HBM only). None = auto by K/V footprint.
    """
    B, H, T, D = q.shape
    scale, rate, seed = _flash_prologue(D, scale, dropout_rate, dropout_rng)
    block_q = _block_for(T, block_q)
    block_k = _block_for(T, block_k)
    if stream is None:
        stream = _should_stream(T, D, jnp.dtype(q.dtype).itemsize)
    if pltpu is None:
        # the streamed grids need pltpu (VMEM scratch, scalar prefetch);
        # on installs without it degrade to the resident kernels, which
        # run everywhere via interpret mode
        stream = False
    fn = _flash_stream if stream else _flash
    return fn(q, k, v, seed, scale, bool(causal), block_q,
              block_k, rate)


# ---------------------------------------------------------------------------
# packed-heads resident family: attention straight off the fused QKV
# projection, no head transposes anywhere
#
# The (B, H, T, D) layout the families above consume costs real HBM: the
# char-GPT HLO carries ~1.1 GB/step of (B,T,H,D)<->(B,H,T,D) transpose
# copies feeding/draining the kernels (benchmarks/RESULTS.md), and a
# per-head 4-d BlockSpec that would read (B,T,H,D) directly is
# Mosaic-unrepresentable (a (1, bq, 1, D) block's trailing dims neither
# divide (8, 128) nor equal the array dims). This family sidesteps the
# layout question entirely: the kernel consumes the QKV projection's own
# (B, T, 3C) output — q as columns [0, C), k [C, 2C), v [2C, 3C), heads
# as D-wide column strips — with grid (B,) and the whole (T, 3C) block
# resident in VMEM. Heads are a static in-kernel loop over lane slices;
# per-head tile math is byte-identical to the unpacked kernels
# (_fwd_tile/_dkv_tile with bh = b * H + h), so dropout masks and
# numerics match the unpacked family bit-for-bit.
#
# The backward emits d(qkv) as one packed (B, T, 3C) array — dq columns
# from a (T, C) f32 VMEM scratch accumulated kv-major (one p/ds
# recompute per tile serves dq, dk and dv, as in the fused kv-major
# kernel above), dk/dv written per kv-row-block — so the gradient flows
# straight into the projection matmul's VJP with no split/concat/
# transpose on either side of either pass.
#
# Residency bound: the whole (T, 3C) block (plus do/dqkv/scratch in the
# backward) must fit VMEM (~16 MB/core), so this family owns the
# short-T/many-head regime (char-GPT: T=256, C=384 -> 0.6 MB) and the
# general (B, H, T, D) families keep everything past PACKED_QKV_BYTES.
# ---------------------------------------------------------------------------

# (T, 3C) itemsize bound for the packed family. The backward's VMEM
# footprint per program is qkv + do + dqkv + (T, C) f32 scratch
# ~= 2.8x the qkv block (bf16), double-buffered across batch programs;
# 2 MiB keeps the worst case ~11 MiB under the ~16 MiB/core budget.
PACKED_QKV_BYTES = 2 * 1024 * 1024


def packed_supported(T: int, C: int, n_head: int, itemsize: int) -> bool:
    """Envelope for the packed-heads family: head strips must be
    lane-sliceable D in {32, 64, 128, 256}, T tileable, and the whole
    (T, 3C) block resident (see PACKED_QKV_BYTES)."""
    if C % n_head != 0:
        return False
    D = C // n_head
    return (D in (32, 64, 128, 256) and T >= 128 and T % 128 == 0
            and T * 3 * C * itemsize <= PACKED_QKV_BYTES)


def _fwd_kernel_packed(seed_ref, qkv_ref, o_ref, lse_ref, *, scale, causal,
                       n_head, head_dim, seq_len, block_q, block_k,
                       dropout_rate):
    b = pl.program_id(0)
    H, D, C = n_head, head_dim, n_head * head_dim
    n_q = seq_len // block_q
    n_kv_total = seq_len // block_k
    for jb in range(n_q):
        q_first = jb * block_q
        rows = slice(jb * block_q, (jb + 1) * block_q)
        if causal:
            n_kv = min((q_first + block_q + block_k - 1) // block_k,
                       n_kv_total)
        else:
            n_kv = n_kv_total
        outs = []
        lses = []
        for h in range(H):
            q = qkv_ref[rows, h * D:(h + 1) * D]
            acc = jnp.zeros((block_q, D), jnp.float32)
            m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
            l = jnp.zeros((block_q, 1), jnp.float32)
            for kb in range(n_kv):
                krows = slice(kb * block_k, (kb + 1) * block_k)
                k = qkv_ref[krows, C + h * D:C + (h + 1) * D]
                v = qkv_ref[krows, 2 * C + h * D:2 * C + (h + 1) * D]
                acc, m, l = _fwd_tile(
                    q, k, v, acc, m, l, scale=scale, causal=causal,
                    q_first=q_first, k_first=kb * block_k,
                    block_q=block_q, block_k=block_k, seed=seed_ref[0],
                    bh=b * H + h, dropout_rate=dropout_rate)
            l = jnp.maximum(l, 1e-30)
            outs.append((acc / l).astype(o_ref.dtype))
            lses.append(m + jnp.log(l))
        o_ref[rows, :] = jnp.concatenate(outs, axis=1)
        lse_ref[rows, :] = jnp.concatenate(lses, axis=1)


def _packed_fwd(qkv, seed, scale, causal, n_head, block_q, block_k,
                dropout_rate):
    B, T, C3 = qkv.shape
    C = C3 // 3
    D = C // n_head
    kernel = functools.partial(
        _fwd_kernel_packed, scale=scale, causal=causal, n_head=n_head,
        head_dim=D, seq_len=T, block_q=block_q, block_k=block_k,
        dropout_rate=dropout_rate)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, T, C3), lambda b: (b, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, T, C), lambda b: (b, 0, 0)),
            _vmem_spec((None, T, n_head), lambda b: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, C), qkv.dtype),
            jax.ShapeDtypeStruct((B, T, n_head), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(seed, qkv)
    return o, lse


def _bwd_kernel_packed(seed_ref, qkv_ref, do_ref, lse_ref, delta_ref,
                       dqkv_ref, dq_scratch, *, scale, causal, n_head,
                       head_dim, seq_len, block_q, block_k, dropout_rate):
    """kv-major fully-fused packed backward: one p/ds recompute per
    (head, q-block, kv-block) tile serves dq (into the (T, C) f32
    scratch), dk and dv (register accumulators over q-blocks, written
    per kv-row-block). Loops are static Python — the residency bound
    keeps n_q * n_kv * H small — so accumulators live in registers."""
    b = pl.program_id(0)
    H, D, C = n_head, head_dim, n_head * head_dim
    n_q = seq_len // block_q
    n_kv = seq_len // block_k
    dq_scratch[...] = jnp.zeros((seq_len, C), jnp.float32)
    for kb in range(n_kv):
        k_first = kb * block_k
        krows = slice(kb * block_k, (kb + 1) * block_k)
        dks = []
        dvs = []
        for h in range(H):
            k = qkv_ref[krows, C + h * D:C + (h + 1) * D]
            v = qkv_ref[krows, 2 * C + h * D:2 * C + (h + 1) * D]
            dk_acc = jnp.zeros((block_k, D), jnp.float32)
            dv_acc = jnp.zeros((block_k, D), jnp.float32)
            jb0 = (k_first // block_q) if causal else 0
            for jb in range(jb0, n_q):
                rows = slice(jb * block_q, (jb + 1) * block_q)
                q = qkv_ref[rows, h * D:(h + 1) * D]
                do = do_ref[rows, h * D:(h + 1) * D]
                lse = lse_ref[rows, h:h + 1]
                delta = delta_ref[rows, h:h + 1]
                dk_c, dv_c, dsc = _dkv_tile(
                    q, k, v, do, lse, delta, scale=scale, causal=causal,
                    q_first=jb * block_q, k_first=k_first,
                    block_q=block_q, block_k=block_k, seed=seed_ref[0],
                    bh=b * H + h, dropout_rate=dropout_rate)
                dk_acc += dk_c
                dv_acc += dv_c
                dq_c = jax.lax.dot_general(
                    dsc, k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                dq_scratch[rows, h * D:(h + 1) * D] += dq_c
            dks.append(dk_acc.astype(dqkv_ref.dtype))
            dvs.append(dv_acc.astype(dqkv_ref.dtype))
        dqkv_ref[krows, C:2 * C] = jnp.concatenate(dks, axis=1)
        dqkv_ref[krows, 2 * C:3 * C] = jnp.concatenate(dvs, axis=1)
    dqkv_ref[:, 0:C] = dq_scratch[...].astype(dqkv_ref.dtype)


def _packed_bwd(qkv, do, lse, delta, seed, scale, causal, n_head, block_q,
                block_k, dropout_rate):
    B, T, C3 = qkv.shape
    C = C3 // 3
    D = C // n_head
    kernel = functools.partial(
        _bwd_kernel_packed, scale=scale, causal=causal, n_head=n_head,
        head_dim=D, seq_len=T, block_q=block_q, block_k=block_k,
        dropout_rate=dropout_rate)
    spec_full = lambda w: _vmem_spec((None, T, w), lambda b: (b, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[_smem_spec(), spec_full(C3), spec_full(C),
                  spec_full(n_head), spec_full(n_head)],
        out_specs=spec_full(C3),
        out_shape=jax.ShapeDtypeStruct((B, T, C3), qkv.dtype),
        scratch_shapes=[_scratch((T, C))],
        interpret=_interpret_mode(),
    )(seed, qkv, do, lse, delta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _flash_packed(qkv, seed, scale, causal, n_head, block_q, block_k,
                  dropout_rate):
    o, _ = _packed_fwd(qkv, seed, scale, causal, n_head, block_q, block_k,
                       dropout_rate)
    return o


def _flash_packed_fwd_rule(qkv, seed, scale, causal, n_head, block_q,
                           block_k, dropout_rate):
    o, lse = _packed_fwd(qkv, seed, scale, causal, n_head, block_q,
                         block_k, dropout_rate)
    return o, (qkv, seed, o, lse)


def _flash_packed_bwd_rule(scale, causal, n_head, block_q, block_k,
                           dropout_rate, residuals, g):
    qkv, seed, o, lse = residuals
    B, T, C = o.shape
    D = C // n_head
    # delta = rowsum(do * o) per head — a minor-dim split + reduce on the
    # packed layout, no transposes (dropout's mask is already inside o,
    # matching the unpacked families' delta semantics)
    delta = (g.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
        B, T, n_head, D).sum(-1)
    dqkv = _packed_bwd(qkv, g.astype(qkv.dtype), lse, delta, seed, scale,
                       causal, n_head, block_q, block_k, dropout_rate)
    return dqkv, None


_flash_packed.defvjp(_flash_packed_fwd_rule, _flash_packed_bwd_rule)


# ---------------------------------------------------------------------------
# packed head-group family: the packed layout past the full-residency bound
#
# The resident packed family above needs the whole (T, 3C) block (plus
# do/dqkv/scratch in the backward, ~2.8x) in VMEM, which caps it at
# PACKED_QKV_BYTES — char-GPT fits (0.6 MB), GPT-2 124M (T=1024, C=768:
# 4.7 MB, backward ~14 MB) does not; Mosaic refuses the allocation
# (benchmarks/RESULTS.md round-3 "measured and rejected" row). This
# family keeps the no-transpose property but shrinks residency from
# O(T*3C) to O(T*W) by splitting heads into lane-aligned GROUPS: a group
# is hpg = max(1, 128 // D) adjacent heads, W = hpg*D in {128, 256}
# columns wide, so the group's q/k/v strips are addressable as last-dim
# BlockSpec blocks of the untouched (B, T, 3C) array (block width W is
# lane-aligned where a bare D=64 head strip is Mosaic-unrepresentable).
# Grid carries (batch, group): each program sees only its (T, W) strips
# — 124M: 256 KB vs the 4.7 MB full block — and loops its hpg sub-heads
# as static in-kernel lane slices, exactly like the resident family
# loops all H. The head->HBM gather that the (B,H,T,D) families pay as
# separate transpose ops happens inside the kernel's double-buffered
# block fetches instead.
#
# Forward grid is (B, G, n_q) with K/V strip index maps independent of
# the q axis (fetched once per (b, g), pipelined across q blocks);
# online-softmax state lives in registers within one grid step — no
# cross-step carry, no scratch state. Backward is the fused kv-major
# form of the resident packed backward on one (b, g) per program: one
# p/ds recompute per (sub-head, q-block, kv-block) serves dq (a (T, W)
# f32 VMEM scratch), dk and dv (register accumulators, written per
# kv-row-block). dq/dk/dv emerge as three (B, T, C) arrays whose
# concatenation is the packed d(qkv) — one contiguous copy, no
# transposes.
#
# Per-head tile math and the dropout counter stream key off
# bh = b*H + (g*hpg + s), identical to every other family, so outputs
# are bit-identical to the unpacked and resident-packed kernels.
#
# LSE layout: narrow (B, G, T, hpg) f32 — one column per sub-head, the
# same equal-to-array-dim trailing block the resident family's (T, H)
# lse output uses. The first cut of this family carried stats
# strip-broadcast (B, G, T, W); at B=64 the extra ~600 MB/layer of lse +
# delta temps pushed the 124M k-step scan 2.7 GB past HBM (measured OOM,
# 18.46/15.75 GB) — narrow stats fit it back (and are 128x less traffic
# than the unpacked families' (B*H, T, LANES) broadcasts).
# ---------------------------------------------------------------------------

# (T, W) strip-residency bound. Backward VMEM per program: q/k/v/do
# strips (4S bf16, S = T*W*itemsize), dq/dk/dv outs (3S), (T, W) f32
# dq scratch (2S), narrow (T, hpg) f32 lse/delta (negligible, ~S/16) —
# ~9S, roughly doubled by block double-buffering; 512 KiB keeps the
# worst case ~9 MiB under the ~16 MiB/core budget with headroom for
# Mosaic's own temporaries. W=128 bf16 -> T <= 2048.
GROUP_STRIP_BYTES = 512 * 1024


def _group_geometry(C: int, n_head: int):
    """(D, heads_per_group, W, n_groups) for the head-group family, or
    None when heads cannot form lane-aligned groups."""
    if C % n_head != 0:
        return None
    D = C // n_head
    if D not in (32, 64, 128, 256):
        return None
    hpg = max(1, 128 // D)
    if n_head % hpg != 0:
        return None
    return D, hpg, hpg * D, n_head // hpg


def packed_group_supported(T: int, C: int, n_head: int,
                           itemsize: int) -> bool:
    """Envelope for the head-group packed family (see GROUP_STRIP_BYTES)."""
    geo = _group_geometry(C, n_head)
    return (geo is not None and T >= 128 and T % 128 == 0
            and T * geo[2] * itemsize <= GROUP_STRIP_BYTES)


def _fwd_kernel_group(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      scale, causal, n_head, head_dim, heads_per_group,
                      seq_len, block_q, block_k, dropout_rate):
    b = pl.program_id(0)
    g = pl.program_id(1)
    jb = pl.program_id(2)
    D = head_dim
    q_first = jb * block_q
    # jb is a grid index (traced), so the causal kv bound is a traced
    # fori_loop bound with pl.ds row slices, as in _fwd_kernel
    if causal:
        n_kv = (q_first + block_q + block_k - 1) // block_k
    else:
        n_kv = seq_len // block_k
    lses = []
    for s in range(heads_per_group):
        cols = slice(s * D, (s + 1) * D)
        q = q_ref[:, cols]
        bh = b * n_head + g * heads_per_group + s

        def body(kb, carry, q=q, bh=bh, cols=cols):
            acc, m, l = carry
            k = k_ref[pl.ds(kb * block_k, block_k), cols]
            v = v_ref[pl.ds(kb * block_k, block_k), cols]
            return _fwd_tile(q, k, v, acc, m, l, scale=scale,
                             causal=causal, q_first=q_first,
                             k_first=kb * block_k, block_q=block_q,
                             block_k=block_k, seed=seed_ref[0], bh=bh,
                             dropout_rate=dropout_rate)

        acc = jnp.zeros((block_q, D), jnp.float32)
        m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        acc, m, l = jax.lax.fori_loop(0, n_kv, body, (acc, m0, l0))
        l = jnp.maximum(l, 1e-30)
        o_ref[:, cols] = (acc / l).astype(o_ref.dtype)
        lses.append(m + jnp.log(l))
    lse_ref[...] = jnp.concatenate(lses, axis=1)


def _group_fwd(qkv, seed, scale, causal, n_head, block_q, block_k,
               dropout_rate):
    B, T, C3 = qkv.shape
    C = C3 // 3
    D, hpg, W, G = _group_geometry(C, n_head)
    kernel = functools.partial(
        _fwd_kernel_group, scale=scale, causal=causal, n_head=n_head,
        head_dim=D, heads_per_group=hpg, seq_len=T, block_q=block_q,
        block_k=block_k, dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(3, 3)
    if cp is not None:
        kw["compiler_params"] = cp
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, G, T // block_q),
        in_specs=[
            _smem_spec(),
            # three W-wide last-dim-blocked views of the one (B, T, 3C)
            # array: q strip g, k strip G + g, v strip 2G + g. K/V maps
            # ignore the q axis, so their fetches amortize across it.
            _vmem_spec((None, block_q, W), lambda b, g, j: (b, j, g)),
            _vmem_spec((None, T, W), lambda b, g, j: (b, 0, G + g)),
            _vmem_spec((None, T, W), lambda b, g, j: (b, 0, 2 * G + g)),
        ],
        out_specs=[
            _vmem_spec((None, block_q, W), lambda b, g, j: (b, j, g)),
            _vmem_spec((None, None, block_q, hpg),
                       lambda b, g, j: (b, g, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, C), qkv.dtype),
            jax.ShapeDtypeStruct((B, G, T, hpg), jnp.float32),
        ],
        interpret=_interpret_mode(),
        **kw,
    )(seed, qkv, qkv, qkv)
    # (B, G, T, hpg) -> (B, H, T) for the residual
    lse_c = lse.transpose(0, 1, 3, 2).reshape(B, n_head, T)
    return o, lse_c


def _group_stats(x, hpg):
    """(B, H, T) per-head rows -> the (B, G, T, hpg) column-per-sub-head
    layout the group kernels read."""
    B, H, T = x.shape
    return x.reshape(B, H // hpg, hpg, T).transpose(0, 1, 3, 2)


def _bwd_kernel_group(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dk_ref, dv_ref, dq_scratch, *,
                      scale, causal, n_head, head_dim, heads_per_group,
                      seq_len, block_q, block_k, dropout_rate):
    b = pl.program_id(0)
    g = pl.program_id(1)
    D, hpg = head_dim, heads_per_group
    W = hpg * D
    n_q = seq_len // block_q
    n_kv = seq_len // block_k
    dq_scratch[...] = jnp.zeros((seq_len, W), jnp.float32)
    for kb in range(n_kv):
        k_first = kb * block_k
        krows = slice(kb * block_k, (kb + 1) * block_k)
        for s in range(hpg):
            cols = slice(s * D, (s + 1) * D)
            k = k_ref[krows, cols]
            v = v_ref[krows, cols]
            dk_acc = jnp.zeros((block_k, D), jnp.float32)
            dv_acc = jnp.zeros((block_k, D), jnp.float32)
            bh = b * n_head + g * hpg + s
            jb0 = (k_first // block_q) if causal else 0
            for jb in range(jb0, n_q):
                rows = slice(jb * block_q, (jb + 1) * block_q)
                dk_c, dv_c, dsc = _dkv_tile(
                    q_ref[rows, cols], k, v, do_ref[rows, cols],
                    lse_ref[rows, s:s + 1],
                    delta_ref[rows, s:s + 1], scale=scale,
                    causal=causal, q_first=jb * block_q, k_first=k_first,
                    block_q=block_q, block_k=block_k, seed=seed_ref[0],
                    bh=bh, dropout_rate=dropout_rate)
                dk_acc += dk_c
                dv_acc += dv_c
                dq_c = jax.lax.dot_general(
                    dsc, k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                dq_scratch[rows, cols] += dq_c
            dk_ref[krows, cols] = dk_acc.astype(dk_ref.dtype)
            dv_ref[krows, cols] = dv_acc.astype(dv_ref.dtype)
    dq_ref[...] = dq_scratch[...].astype(dq_ref.dtype)


def _group_bwd(qkv, do, lse_c, delta_c, seed, scale, causal, n_head,
               block_q, block_k, dropout_rate):
    B, T, C3 = qkv.shape
    C = C3 // 3
    D, hpg, W, G = _group_geometry(C, n_head)
    lse4 = _group_stats(lse_c, hpg)
    delta4 = _group_stats(delta_c, hpg)
    kernel = functools.partial(
        _bwd_kernel_group, scale=scale, causal=causal, n_head=n_head,
        head_dim=D, heads_per_group=hpg, seq_len=T, block_q=block_q,
        block_k=block_k, dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(2, 2)
    if cp is not None:
        kw["compiler_params"] = cp
    strip = lambda blk: _vmem_spec((None, T, W), lambda b, g: (b, 0, blk(g)))
    stat = _vmem_spec((None, None, T, hpg), lambda b, g: (b, g, 0, 0))
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(B, G),
        in_specs=[_smem_spec(),
                  strip(lambda g: g), strip(lambda g: G + g),
                  strip(lambda g: 2 * G + g), strip(lambda g: g),
                  stat, stat],
        out_specs=[strip(lambda g: g)] * 3,
        out_shape=[jax.ShapeDtypeStruct((B, T, C), qkv.dtype)] * 3,
        scratch_shapes=[_scratch((T, W))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, qkv, qkv, qkv, do, lse4, delta4)
    return jnp.concatenate([dq, dk, dv], axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _flash_packed_group(qkv, seed, scale, causal, n_head, block_q, block_k,
                        dropout_rate):
    o, _ = _group_fwd(qkv, seed, scale, causal, n_head, block_q, block_k,
                      dropout_rate)
    return o


def _flash_packed_group_fwd_rule(qkv, seed, scale, causal, n_head, block_q,
                                 block_k, dropout_rate):
    o, lse_c = _group_fwd(qkv, seed, scale, causal, n_head, block_q,
                          block_k, dropout_rate)
    return o, (qkv, seed, o, lse_c)


def _flash_packed_group_bwd_rule(scale, causal, n_head, block_q, block_k,
                                 dropout_rate, residuals, g):
    qkv, seed, o, lse_c = residuals
    B, T, C = o.shape
    D = C // n_head
    # delta = rowsum(do * o) per head, straight off the packed layout
    delta_c = (g.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
        B, T, n_head, D).sum(-1).transpose(0, 2, 1)
    dqkv = _group_bwd(qkv, g.astype(qkv.dtype), lse_c, delta_c, seed,
                      scale, causal, n_head, block_q, block_k, dropout_rate)
    return dqkv, None


_flash_packed_group.defvjp(_flash_packed_group_fwd_rule,
                           _flash_packed_group_bwd_rule)


# ---------------------------------------------------------------------------
# streamed head-group family: the packed layout past GROUP_STRIP_BYTES
#
# The group family above still holds one (T, W) K/V strip resident per
# (b, g) program, capping it at T <= 2048 (W=128 bf16) — past that, the
# packed path fell back to the unpacked streamed family and long-context
# runs paid the (B,T,H,D)<->(B,H,T,D) layout round trips again. This
# family combines the two existing techniques: the kv axis joins the
# pallas grid with the online-softmax state carried in VMEM scratch
# (exactly the streamed family, _fwd_kernel_stream) while the q/k/v
# operands stay W-wide last-dim BlockSpec strips of the untouched
# (B, T, 3C) array (exactly the group family). VMEM is O(block*W)
# regardless of T, so packed long-T is bounded by HBM only.
#
# Per-sub-head m/l state rides the (block_q, W) scratch broadcast across
# each sub-head's D-column slice (the D-narrow analogue of the unpacked
# stream family's LANES-broadcast stats); dq accumulates across kv grid
# steps in a (block_q, W) scratch, dk/dv across q grid steps in
# (block_k, W) scratches — the dq/dkv kernel split of the streamed
# family, since a kv-major fused dq scratch would be (T, W) f32 and
# grow with T again. Tile math and the bh = b*H + g*hpg + s dropout
# counter are shared with every other family: outputs are bit-identical
# (asserted in tests/test_flash_attention.py group_stream section).
# Causal with block_q == block_k (the default) takes the
# scalar-prefetched triangular tile map (further below) — masked tiles'
# fetches and grid steps disappear, as in the unpacked tri kernels; the
# rectangular grid remains for non-causal / unequal-block calls, where
# pl.when skips masked tiles' matmuls but not their fetches.
# ---------------------------------------------------------------------------


# Auto-route gate for the streamed head-group family. False keeps the
# family OPT-IN (family="group_stream") and off the production routing —
# both the family=None dispatch below and ops.flash_attention's
# packed_envelope_ok read it. Flip to True only once hw_validate's
# compile4k / compile32k / parity4k phases PASS under real Mosaic
# lowering: this codebase has already shipped a (T,)-stats layout that
# interpret mode accepted and Mosaic rejected, so interpret-mode proof
# alone must not put a kernel family on the default path (long-context
# runs would trade the proven unpacked streamed family for a possible
# compile failure at merge).
GROUP_STREAM_AUTOROUTE = False


def packed_group_stream_supported(T: int, C: int, n_head: int,
                                  itemsize: int) -> bool:
    """Envelope for the streamed head-group family: lane-aligned groups
    and block-divisible T — no residency bound (state is O(block*W))."""
    del itemsize
    return (_group_geometry(C, n_head) is not None
            and T >= 128 and T % 128 == 0)


def _fwd_kernel_group_stream(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                             acc_ref, m_ref, l_ref, *, scale, causal,
                             n_head, head_dim, heads_per_group, seq_len,
                             block_q, block_k, dropout_rate):
    b = pl.program_id(0)
    g = pl.program_id(1)
    j = pl.program_id(2)
    kb = pl.program_id(3)
    D, hpg = head_dim, heads_per_group
    n_kv = seq_len // block_k
    q_first = j * block_q
    k_first = kb * block_k
    last_kb = (((j + 1) * block_q - 1) // block_k) if causal else n_kv - 1

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    needed = (k_first <= q_first + block_q - 1) if causal else kb >= 0

    @pl.when(needed)
    def _update():
        for s in range(hpg):
            cols = slice(s * D, (s + 1) * D)
            acc, m_new, l_new = _fwd_tile(
                q_ref[:, cols], k_ref[:, cols], v_ref[:, cols],
                acc_ref[:, cols], m_ref[:, cols][:, :1],
                l_ref[:, cols][:, :1], scale=scale, causal=causal,
                q_first=q_first, k_first=k_first, block_q=block_q,
                block_k=block_k, seed=seed_ref[0],
                bh=b * n_head + g * hpg + s, dropout_rate=dropout_rate)
            acc_ref[:, cols] = acc
            m_ref[:, cols] = jnp.broadcast_to(m_new, (block_q, D))
            l_ref[:, cols] = jnp.broadcast_to(l_new, (block_q, D))

    @pl.when(kb == last_kb)
    def _finalize():
        lses = []
        for s in range(hpg):
            cols = slice(s * D, (s + 1) * D)
            m = m_ref[:, cols][:, :1]
            l = jnp.maximum(l_ref[:, cols][:, :1], 1e-30)
            o_ref[:, cols] = (acc_ref[:, cols] / l).astype(o_ref.dtype)
            lses.append(m + jnp.log(l))
        lse_ref[...] = jnp.concatenate(lses, axis=1)


def _group_fwd_stream(qkv, seed, scale, causal, n_head, block_q, block_k,
                      dropout_rate):
    B, T, C3 = qkv.shape
    C = C3 // 3
    D, hpg, W, G = _group_geometry(C, n_head)
    kernel = functools.partial(
        _fwd_kernel_group_stream, scale=scale, causal=causal,
        n_head=n_head, head_dim=D, heads_per_group=hpg, seq_len=T,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(3, 4)
    if cp is not None:
        kw["compiler_params"] = cp
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, G, T // block_q, T // block_k),
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, block_q, W), lambda b, g, j, kb: (b, j, g)),
            _vmem_spec((None, block_k, W),
                       lambda b, g, j, kb: (b, kb, G + g)),
            _vmem_spec((None, block_k, W),
                       lambda b, g, j, kb: (b, kb, 2 * G + g)),
        ],
        out_specs=[
            _vmem_spec((None, block_q, W), lambda b, g, j, kb: (b, j, g)),
            _vmem_spec((None, None, block_q, hpg),
                       lambda b, g, j, kb: (b, g, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, C), qkv.dtype),
            jax.ShapeDtypeStruct((B, G, T, hpg), jnp.float32),
        ],
        scratch_shapes=[_scratch((block_q, W)), _scratch((block_q, W)),
                        _scratch((block_q, W))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, qkv, qkv, qkv)
    lse_c = lse.transpose(0, 1, 3, 2).reshape(B, n_head, T)
    return o, lse_c


def _bwd_dq_kernel_group_stream(seed_ref, q_ref, k_ref, v_ref, do_ref,
                                lse_ref, delta_ref, dq_ref, dq_acc_ref, *,
                                scale, causal, n_head, head_dim,
                                heads_per_group, seq_len, block_q, block_k,
                                dropout_rate):
    b = pl.program_id(0)
    g = pl.program_id(1)
    j = pl.program_id(2)
    kb = pl.program_id(3)
    D, hpg = head_dim, heads_per_group
    n_kv = seq_len // block_k
    q_first = j * block_q
    k_first = kb * block_k
    last_kb = (((j + 1) * block_q - 1) // block_k) if causal else n_kv - 1

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    needed = (k_first <= q_first + block_q - 1) if causal else kb >= 0

    @pl.when(needed)
    def _update():
        for s in range(hpg):
            cols = slice(s * D, (s + 1) * D)
            dq_acc_ref[:, cols] = dq_acc_ref[:, cols] + _dq_tile(
                q_ref[:, cols], k_ref[:, cols], v_ref[:, cols],
                do_ref[:, cols], lse_ref[:, s:s + 1],
                delta_ref[:, s:s + 1], scale=scale, causal=causal,
                q_first=q_first, k_first=k_first, block_q=block_q,
                block_k=block_k, seed=seed_ref[0],
                bh=b * n_head + g * hpg + s, dropout_rate=dropout_rate)

    @pl.when(kb == last_kb)
    def _finalize():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_group_stream(seed_ref, q_ref, k_ref, v_ref, do_ref,
                                 lse_ref, delta_ref, dk_ref, dv_ref,
                                 dk_acc_ref, dv_acc_ref, *, scale, causal,
                                 n_head, head_dim, heads_per_group, seq_len,
                                 block_q, block_k, dropout_rate):
    b = pl.program_id(0)
    g = pl.program_id(1)
    kb = pl.program_id(2)
    jb = pl.program_id(3)
    D, hpg = head_dim, heads_per_group
    n_q = seq_len // block_q
    k_first = kb * block_k
    q_first = jb * block_q

    @pl.when(jb == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    needed = (q_first + block_q - 1 >= k_first) if causal else jb >= 0

    @pl.when(needed)
    def _update():
        for s in range(hpg):
            cols = slice(s * D, (s + 1) * D)
            dk_c, dv_c, _ = _dkv_tile(
                q_ref[:, cols], k_ref[:, cols], v_ref[:, cols],
                do_ref[:, cols], lse_ref[:, s:s + 1],
                delta_ref[:, s:s + 1], scale=scale, causal=causal,
                q_first=q_first, k_first=k_first, block_q=block_q,
                block_k=block_k, seed=seed_ref[0],
                bh=b * n_head + g * hpg + s, dropout_rate=dropout_rate)
            dk_acc_ref[:, cols] = dk_acc_ref[:, cols] + dk_c
            dv_acc_ref[:, cols] = dv_acc_ref[:, cols] + dv_c

    @pl.when(jb == n_q - 1)
    def _finalize():
        dk_ref[...] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _group_bwd_stream(qkv, do, lse_c, delta_c, seed, scale, causal, n_head,
                      block_q, block_k, dropout_rate):
    B, T, C3 = qkv.shape
    C = C3 // 3
    D, hpg, W, G = _group_geometry(C, n_head)
    lse4 = _group_stats(lse_c, hpg)
    delta4 = _group_stats(delta_c, hpg)
    common = dict(scale=scale, causal=causal, n_head=n_head, head_dim=D,
                  heads_per_group=hpg, seq_len=T, block_q=block_q,
                  block_k=block_k, dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(3, 4)
    if cp is not None:
        kw["compiler_params"] = cp
    qs = lambda blk: _vmem_spec((None, block_q, W),
                                lambda b, g, j, kb: (b, j, blk(g)))
    ks = lambda blk: _vmem_spec((None, block_k, W),
                                lambda b, g, j, kb: (b, kb, blk(g)))
    stat_q = _vmem_spec((None, None, block_q, hpg),
                        lambda b, g, j, kb: (b, g, j, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_group_stream, **common),
        grid=(B, G, T // block_q, T // block_k),
        in_specs=[_smem_spec(), qs(lambda g: g), ks(lambda g: G + g),
                  ks(lambda g: 2 * G + g), qs(lambda g: g), stat_q, stat_q],
        out_specs=qs(lambda g: g),
        out_shape=jax.ShapeDtypeStruct((B, T, C), qkv.dtype),
        scratch_shapes=[_scratch((block_q, W))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, qkv, qkv, qkv, do, lse4, delta4)

    # kv-major grid: q/do/stat maps swap roles (kb outer, jb carried)
    qs2 = lambda blk: _vmem_spec((None, block_q, W),
                                 lambda b, g, kb, jb: (b, jb, blk(g)))
    ks2 = lambda blk: _vmem_spec((None, block_k, W),
                                 lambda b, g, kb, jb: (b, kb, blk(g)))
    stat_q2 = _vmem_spec((None, None, block_q, hpg),
                         lambda b, g, kb, jb: (b, g, jb, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_group_stream, **common),
        grid=(B, G, T // block_k, T // block_q),
        in_specs=[_smem_spec(), qs2(lambda g: g), ks2(lambda g: G + g),
                  ks2(lambda g: 2 * G + g), qs2(lambda g: g), stat_q2,
                  stat_q2],
        out_specs=[ks2(lambda g: g), ks2(lambda g: g)],
        out_shape=[jax.ShapeDtypeStruct((B, T, C), qkv.dtype)] * 2,
        scratch_shapes=[_scratch((block_k, W)), _scratch((block_k, W))],
        interpret=_interpret_mode(),
        **kw,
    )(seed, qkv, qkv, qkv, do, lse4, delta4)
    return jnp.concatenate([dq, dk, dv], axis=-1)


# --- triangular causal grid for the streamed group family ------------------
#
# Same optimization as the unpacked tri kernels above: the rectangular
# (B, G, n_q, n_kv) grid fetches K/V strips for every tile including the
# ~half causal masking discards. For causal with block_q == block_k the
# tile axis flattens to the lower triangle via the scalar-prefetched
# (2, M) tile map — fetches and grid steps for masked tiles disappear.


def _fwd_kernel_group_tri(tmap_ref, seed_ref, q_ref, k_ref, v_ref, o_ref,
                          lse_ref, acc_ref, m_ref, l_ref, *, scale, n_head,
                          head_dim, heads_per_group, block, dropout_rate):
    b = pl.program_id(0)
    g = pl.program_id(1)
    t = pl.program_id(2)
    j = tmap_ref[0, t]
    kb = tmap_ref[1, t]
    D, hpg = head_dim, heads_per_group

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    for s in range(hpg):
        cols = slice(s * D, (s + 1) * D)
        acc, m_new, l_new = _fwd_tile(
            q_ref[:, cols], k_ref[:, cols], v_ref[:, cols],
            acc_ref[:, cols], m_ref[:, cols][:, :1], l_ref[:, cols][:, :1],
            scale=scale, causal=True, q_first=j * block, k_first=kb * block,
            block_q=block, block_k=block, seed=seed_ref[0],
            bh=b * n_head + g * hpg + s, dropout_rate=dropout_rate)
        acc_ref[:, cols] = acc
        m_ref[:, cols] = jnp.broadcast_to(m_new, (block, D))
        l_ref[:, cols] = jnp.broadcast_to(l_new, (block, D))

    @pl.when(kb == j)
    def _finalize():
        lses = []
        for s in range(hpg):
            cols = slice(s * D, (s + 1) * D)
            m = m_ref[:, cols][:, :1]
            l = jnp.maximum(l_ref[:, cols][:, :1], 1e-30)
            o_ref[:, cols] = (acc_ref[:, cols] / l).astype(o_ref.dtype)
            lses.append(m + jnp.log(l))
        lse_ref[...] = jnp.concatenate(lses, axis=1)


def _group_fwd_tri(qkv, seed, scale, n_head, block, dropout_rate):
    B, T, C3 = qkv.shape
    C = C3 // 3
    D, hpg, W, G = _group_geometry(C, n_head)
    n = T // block
    tmap = jnp.asarray(_tri_tile_map(n, kv_major=False))
    kernel = functools.partial(
        _fwd_kernel_group_tri, scale=scale, n_head=n_head, head_dim=D,
        heads_per_group=hpg, block=block, dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(2, 3)
    if cp is not None:
        kw["compiler_params"] = cp
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, G, tmap.shape[1]),
        in_specs=[
            _vmem_spec((None, block, W),
                       lambda b, g, t, tm, sd: (b, tm[0, t], g)),
            _vmem_spec((None, block, W),
                       lambda b, g, t, tm, sd: (b, tm[1, t], G + g)),
            _vmem_spec((None, block, W),
                       lambda b, g, t, tm, sd: (b, tm[1, t], 2 * G + g)),
        ],
        out_specs=[
            _vmem_spec((None, block, W),
                       lambda b, g, t, tm, sd: (b, tm[0, t], g)),
            _vmem_spec((None, None, block, hpg),
                       lambda b, g, t, tm, sd: (b, g, tm[0, t], 0)),
        ],
        scratch_shapes=[_scratch((block, W)), _scratch((block, W)),
                        _scratch((block, W))],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, T, C), qkv.dtype),
            jax.ShapeDtypeStruct((B, G, T, hpg), jnp.float32),
        ],
        interpret=_interpret_mode(),
        **kw,
    )(tmap, seed, qkv, qkv, qkv)
    lse_c = lse.transpose(0, 1, 3, 2).reshape(B, n_head, T)
    return o, lse_c


def _bwd_dq_kernel_group_tri(tmap_ref, seed_ref, q_ref, k_ref, v_ref,
                             do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
                             *, scale, n_head, head_dim, heads_per_group,
                             block, dropout_rate):
    b = pl.program_id(0)
    g = pl.program_id(1)
    t = pl.program_id(2)
    j = tmap_ref[0, t]
    kb = tmap_ref[1, t]
    D, hpg = head_dim, heads_per_group

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    for s in range(hpg):
        cols = slice(s * D, (s + 1) * D)
        dq_acc_ref[:, cols] = dq_acc_ref[:, cols] + _dq_tile(
            q_ref[:, cols], k_ref[:, cols], v_ref[:, cols], do_ref[:, cols],
            lse_ref[:, s:s + 1], delta_ref[:, s:s + 1], scale=scale,
            causal=True, q_first=j * block, k_first=kb * block,
            block_q=block, block_k=block, seed=seed_ref[0],
            bh=b * n_head + g * hpg + s, dropout_rate=dropout_rate)

    @pl.when(kb == j)
    def _finalize():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_group_tri(tmap_ref, seed_ref, q_ref, k_ref, v_ref,
                              do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                              dk_acc_ref, dv_acc_ref, *, scale, n_head,
                              head_dim, heads_per_group, block, n_q,
                              dropout_rate):
    b = pl.program_id(0)
    g = pl.program_id(1)
    t = pl.program_id(2)
    kb = tmap_ref[0, t]
    jb = tmap_ref[1, t]
    D, hpg = head_dim, heads_per_group

    @pl.when(jb == kb)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    for s in range(hpg):
        cols = slice(s * D, (s + 1) * D)
        dk_c, dv_c, _ = _dkv_tile(
            q_ref[:, cols], k_ref[:, cols], v_ref[:, cols], do_ref[:, cols],
            lse_ref[:, s:s + 1], delta_ref[:, s:s + 1], scale=scale,
            causal=True, q_first=jb * block, k_first=kb * block,
            block_q=block, block_k=block, seed=seed_ref[0],
            bh=b * n_head + g * hpg + s, dropout_rate=dropout_rate)
        dk_acc_ref[:, cols] = dk_acc_ref[:, cols] + dk_c
        dv_acc_ref[:, cols] = dv_acc_ref[:, cols] + dv_c

    @pl.when(jb == n_q - 1)
    def _finalize():
        dk_ref[...] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _group_bwd_tri(qkv, do, lse_c, delta_c, seed, scale, n_head, block,
                   dropout_rate):
    B, T, C3 = qkv.shape
    C = C3 // 3
    D, hpg, W, G = _group_geometry(C, n_head)
    n = T // block
    lse4 = _group_stats(lse_c, hpg)
    delta4 = _group_stats(delta_c, hpg)
    common = dict(scale=scale, n_head=n_head, head_dim=D,
                  heads_per_group=hpg, block=block,
                  dropout_rate=dropout_rate)
    kw = {}
    cp = _compiler_params(2, 3)
    if cp is not None:
        kw["compiler_params"] = cp

    tmap_q = jnp.asarray(_tri_tile_map(n, kv_major=False))
    # tm[0] = q-block (carried), tm[1] = kv-block
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_group_tri, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, G, tmap_q.shape[1]),
            in_specs=[
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[0, t], g)),
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[1, t], G + g)),
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[1, t], 2 * G + g)),
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[0, t], g)),
                _vmem_spec((None, None, block, hpg),
                           lambda b, g, t, tm, sd: (b, g, tm[0, t], 0)),
                _vmem_spec((None, None, block, hpg),
                           lambda b, g, t, tm, sd: (b, g, tm[0, t], 0)),
            ],
            out_specs=_vmem_spec((None, block, W),
                                 lambda b, g, t, tm, sd: (b, tm[0, t], g)),
            scratch_shapes=[_scratch((block, W))],
        ),
        out_shape=jax.ShapeDtypeStruct((B, T, C), qkv.dtype),
        interpret=_interpret_mode(),
        **kw,
    )(tmap_q, seed, qkv, qkv, qkv, do, lse4, delta4)

    tmap_kv = jnp.asarray(_tri_tile_map(n, kv_major=True))
    # tm[0] = kv-block (carried), tm[1] = q-block
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_group_tri, n_q=n, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, G, tmap_kv.shape[1]),
            in_specs=[
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[1, t], g)),
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[0, t], G + g)),
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[0, t], 2 * G + g)),
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[1, t], g)),
                _vmem_spec((None, None, block, hpg),
                           lambda b, g, t, tm, sd: (b, g, tm[1, t], 0)),
                _vmem_spec((None, None, block, hpg),
                           lambda b, g, t, tm, sd: (b, g, tm[1, t], 0)),
            ],
            out_specs=[
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[0, t], g)),
                _vmem_spec((None, block, W),
                           lambda b, g, t, tm, sd: (b, tm[0, t], g)),
            ],
            scratch_shapes=[_scratch((block, W)), _scratch((block, W))],
        ),
        out_shape=[jax.ShapeDtypeStruct((B, T, C), qkv.dtype)] * 2,
        interpret=_interpret_mode(),
        **kw,
    )(tmap_kv, seed, qkv, qkv, qkv, do, lse4, delta4)
    return jnp.concatenate([dq, dk, dv], axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _flash_packed_group_stream(qkv, seed, scale, causal, n_head, block_q,
                               block_k, dropout_rate):
    if _tri_eligible(causal, block_q, block_k):
        o, _ = _group_fwd_tri(qkv, seed, scale, n_head, block_q,
                              dropout_rate)
    else:
        o, _ = _group_fwd_stream(qkv, seed, scale, causal, n_head, block_q,
                                 block_k, dropout_rate)
    return o


def _flash_packed_group_stream_fwd_rule(qkv, seed, scale, causal, n_head,
                                        block_q, block_k, dropout_rate):
    if _tri_eligible(causal, block_q, block_k):
        o, lse_c = _group_fwd_tri(qkv, seed, scale, n_head, block_q,
                                  dropout_rate)
    else:
        o, lse_c = _group_fwd_stream(qkv, seed, scale, causal, n_head,
                                     block_q, block_k, dropout_rate)
    return o, (qkv, seed, o, lse_c)


def _flash_packed_group_stream_bwd_rule(scale, causal, n_head, block_q,
                                        block_k, dropout_rate, residuals, g):
    qkv, seed, o, lse_c = residuals
    B, T, C = o.shape
    D = C // n_head
    delta_c = (g.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
        B, T, n_head, D).sum(-1).transpose(0, 2, 1)
    if _tri_eligible(causal, block_q, block_k):
        dqkv = _group_bwd_tri(qkv, g.astype(qkv.dtype), lse_c, delta_c,
                              seed, scale, n_head, block_q, dropout_rate)
    else:
        dqkv = _group_bwd_stream(qkv, g.astype(qkv.dtype), lse_c, delta_c,
                                 seed, scale, causal, n_head, block_q,
                                 block_k, dropout_rate)
    return dqkv, None


_flash_packed_group_stream.defvjp(_flash_packed_group_stream_fwd_rule,
                                  _flash_packed_group_stream_bwd_rule)


def pallas_flash_attention_packed(qkv: jnp.ndarray, n_head: int, *,
                                  scale: Optional[float] = None,
                                  causal: bool = True,
                                  block_q: Optional[int] = None,
                                  block_k: Optional[int] = None,
                                  dropout_rate: float = 0.0,
                                  dropout_rng: Optional[jax.Array] = None,
                                  family: Optional[str] = None
                                  ) -> jnp.ndarray:
    """Packed-heads flash attention. qkv: (B, T, 3C) — the fused QKV
    projection output, untouched. Returns the merged (B, T, C) attention
    output, ready for the output projection. Numerics (including the
    in-kernel dropout stream) are bit-identical to
    ``pallas_flash_attention`` on the same logical q/k/v.

    Routes by residency: the fully-resident family while (T, 3C) fits
    PACKED_QKV_BYTES (short-T/many-head, e.g. char-GPT), the head-group
    family while (T, W) strips fit GROUP_STRIP_BYTES (GPT-2-scale
    T=1024), and the streamed head-group family past that (long-T:
    state in VMEM scratch, T bounded by HBM only). ``family``
    ('resident' | 'group' | 'group_stream') overrides the routing — for
    parity tests and for benchmarking the families against each other
    on shapes both support."""
    B, T, C3 = qkv.shape
    C = C3 // 3
    D = C // n_head
    scale, rate, seed = _flash_prologue(D, scale, dropout_rate, dropout_rng)
    block_q = _block_for(T, block_q)
    block_k = _block_for(T, block_k)
    itemsize = jnp.dtype(qkv.dtype).itemsize
    if family is None:
        family = ("resident" if packed_supported(T, C, n_head, itemsize)
                  else "group" if packed_group_supported(T, C, n_head,
                                                        itemsize)
                  else "group_stream" if (
                      GROUP_STREAM_AUTOROUTE
                      and packed_group_stream_supported(T, C, n_head,
                                                        itemsize))
                  else None)
    if family == "resident":
        return _flash_packed(qkv, seed, scale, bool(causal), n_head,
                             block_q, block_k, rate)
    if family in ("group", "group_stream"):
        if _group_geometry(C, n_head) is None:
            raise ValueError(f"no lane-aligned head groups for C={C}, "
                             f"n_head={n_head}")
        fn = (_flash_packed_group if family == "group"
              else _flash_packed_group_stream)
        return fn(qkv, seed, scale, bool(causal), n_head, block_q, block_k,
                  rate)
    raise ValueError(
        f"packed families do not support T={T}, C={C}, n_head={n_head}; "
        "gate callers on ops.flash_attention.packed_envelope_ok")
