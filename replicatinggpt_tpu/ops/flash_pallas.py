"""Pallas TPU flash attention: blockwise online-softmax, fwd + custom-VJP bwd.

Replaces the O(T^2)-HBM attention the reference materializes per head
(GPT1.py:114-116) with a fused kernel that keeps only (block_q, block_k)
score tiles in VMEM. Forward follows the standard flash algorithm (running
max m, running normalizer l, rescaled accumulator); backward recomputes
score tiles blockwise from the saved logsumexp, producing dq in a q-major
kernel and dk/dv in a kv-major kernel (no stored attention matrix anywhere).

Layout notes (TPU): all tiles are (128, D) with D in {32, 64, 128, 256};
score tiles are (128, 128) → MXU-native. LSE/delta are per-row scalars,
which Mosaic cannot tile as a bare (T,) lane — they are carried
broadcast across a LANES-wide trailing dim ((BH, T, LANES) arrays,
(block_q, LANES) tiles), the same layout the reference TPU flash kernel
in jax.experimental.pallas.ops.tpu uses for its m/l stats.
Causal masking skips fully-masked kv blocks entirely (the fori_loop upper
bound is derived from the q-block index), so the kernel does ~half the
FLOPs of the dense path on causal workloads.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

BLOCK = 128
LANES = 128  # trailing width for per-row stats (Mosaic lane alignment)
NEG_INF = -1e30


def _vmem_spec(block_shape, index_map):
    kw = {"memory_space": _VMEM} if _VMEM is not None else {}
    return pl.BlockSpec(block_shape, index_map, **kw)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                seq_len, block_q, block_k):
    j = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * scale          # (bq, D)
    D = q.shape[-1]
    q_first = j * block_q

    if causal:
        n_kv = (q_first + block_q + block_k - 1) // block_k
    else:
        n_kv = seq_len // block_k

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if causal:
            qpos = q_first + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kv, body, (acc, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l), (block_q, LANES))


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    B, H, T, D = q.shape
    BH = B * H
    qf = q.reshape(BH, T, D)
    kf = k.reshape(BH, T, D)
    vf = v.reshape(BH, T, D)
    grid = (BH, T // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               seq_len=T, block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(qf, kf, vf)
    return o.reshape(B, H, T, D), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, seq_len, block_q, block_k):
    j = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)                   # (bq, D)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[...][:, :1]                            # (bq, 1) of (bq, LANES)
    delta = delta_ref[...][:, :1]
    q_first = j * block_q
    if causal:
        n_kv = (q_first + block_q + block_k - 1) // block_k
    else:
        n_kv = seq_len // block_k

    def body(kb, dq):
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = q_first + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)                             # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_kv,
                           body, jnp.zeros_like(q))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, seq_len, block_q,
                    block_k):
    kb = pl.program_id(1)
    k = k_ref[...].astype(jnp.float32)                   # (bk, D)
    v = v_ref[...].astype(jnp.float32)
    k_first = kb * block_k
    n_q = seq_len // block_q
    first_q = (k_first // block_q) if causal else 0

    def body(jb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(jb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(jb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(jb * block_q, block_q), :][:, :1]
        delta = delta_ref[pl.ds(jb * block_q, block_q), :][:, :1]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if causal:
            qpos = jb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = k_first + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, D)
        return dk, dv

    dk0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv0 = jnp.zeros_like(dk0)
    dk, dv = jax.lax.fori_loop(first_q, n_q, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(scale, causal, block_q, block_k, residuals, g):
    q, k, v, o, lse = residuals  # lse: (BH, T) — see _flash_fwd_rule
    B, H, T, D = q.shape
    BH = B * H
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1).reshape(BH, T)
    # stats ride a LANES-wide trailing dim (see module docstring) — but
    # only transiently, materialized here just before the kernels; the
    # per-layer residual that lives across the whole backward pass is the
    # compact (BH, T) form (128x less HBM)
    delta = jnp.broadcast_to(delta[:, :, None], (BH, T, LANES))
    lse = jnp.broadcast_to(lse[:, :, None], (BH, T, LANES))
    qf, kf, vf = (t.reshape(BH, T, D) for t in (q, k, v))
    gf = g.reshape(BH, T, D)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, T // block_q),
        in_specs=[
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
        ],
        out_specs=_vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret_mode(),
    )(qf, kf, vf, gf, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, T // block_k),
        in_specs=[
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, LANES), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, LANES), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        interpret=_interpret_mode(),
    )(qf, kf, vf, gf, lse, delta)

    shape = (B, H, T, D)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

_INTERPRET = False


def _interpret_mode() -> bool:
    return _INTERPRET or jax.default_backend() != "tpu"


def set_interpret(flag: bool) -> None:
    """Force interpreter mode (CPU testing)."""
    global _INTERPRET
    _INTERPRET = flag


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    # keep the residual compact: the kernel emits lse LANES-broadcast
    # ((BH,T,LANES), a Mosaic tiling requirement), but storing that per
    # layer until the backward pass wastes 128x the HBM — save (BH, T)
    # and rebroadcast in _flash_bwd
    return o, (q, k, v, o, lse[..., 0])


def _flash_bwd_rule(scale, causal, block_q, block_k, residuals, g):
    return _flash_bwd(scale, causal, block_q, block_k, residuals, g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def pallas_flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                           scale: Optional[float] = None,
                           causal: bool = True,
                           block_q: int = BLOCK,
                           block_k: int = BLOCK) -> jnp.ndarray:
    """Flash attention. q,k,v: (B, H, T, D); T must be a multiple of the
    block sizes (callers pad or fall back to the einsum path otherwise)."""
    B, H, T, D = q.shape
    if scale is None:
        scale = D ** -0.5
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, (T, block_q, block_k)
    return _flash(q, k, v, float(scale), bool(causal), block_q, block_k)
