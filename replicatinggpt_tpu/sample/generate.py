"""Autoregressive generation: KV-cached, jit-compiled, O(T) work per token.

Capability parity with the reference's two samplers, re-designed for XLA:

- multinomial sampling from the last position's softmax
  (``BigramLanguageModel.generate``, GPT1.py:196-212) — but without the
  O(T^2)-per-token full re-forward: a single ``lax.scan`` teacher-forces
  through the prompt (filling the KV cache) and then emits one sampled token
  per step against the cache;
- temperature / top-k sampling (the reference's dead GPT-2 sampler used
  top-k=50, GPT-2.py:245-247);
- greedy decoding (argmax) as the deterministic mode.

Long generations (beyond ``block_size``, e.g. the reference's 500-token
char-GPT sample with block 256, GPT1.py:236, or the BASELINE.json 1k-token
latency workload) use **window refresh**: when the cache fills, the last
``block_size//2`` tokens are re-prefilled and decoding continues. The
reference instead crops the window per token (GPT1.py:200), which shifts
every absolute position each step and therefore cannot be KV-cached at all
with learned positional embeddings; window refresh keeps the same effective
context length with amortized O(1) full forwards per half-window. This is a
documented deviation (same capability, cache-compatible semantics).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..models.gpt import (_all_single_device, cache_seq_axis, decode_step,
                          init_kv_cache, prefill)


@dataclass(frozen=True)
class GenerateConfig:
    max_new_tokens: int = 500          # GPT1.py:236 default workload
    temperature: float = 1.0
    top_k: int = 0                     # 0 = full multinomial (GPT1.py:208);
                                       # 50 = the GPT-2 sampler (GPT-2.py:245)
    top_p: float = 0.0                 # 0 = off; (0, 1] = nucleus sampling
                                       # (beyond the reference's samplers;
                                       # composes with top_k: k-filter first)
    greedy: bool = False
    attend_granule: int = 128          # KV-cache growth granule for the
                                       # chunked decode scan (_decode_chunks);
                                       # block_size = the monolithic
                                       # full-bucket scan. Lives here (a
                                       # static jit arg) so changing it keys
                                       # a fresh compile — a module global
                                       # read at trace time silently reused
                                       # stale chunking across mutations.


def _sortable_f32(x: jnp.ndarray) -> jnp.ndarray:
    """float32 -> uint32 with the same total order (monotone bijection):
    flip all bits of negatives, set the sign bit of non-negatives. -inf
    maps near 0, +inf near 2^32-1."""
    u = jax.lax.bitcast_convert_type(x, jnp.int32)
    return jnp.where(u < 0, ~u, u | jnp.int32(-2 ** 31)).astype(jnp.uint32)


def _unsortable_f32(u: jnp.ndarray) -> jnp.ndarray:
    i = u.astype(jnp.int32)
    back = jnp.where(i < 0, i & jnp.int32(2 ** 31 - 1), ~i)
    return jax.lax.bitcast_convert_type(back, jnp.float32)


def _kth_largest(logits: jnp.ndarray, k) -> jnp.ndarray:
    """Exact per-row k-th largest of (B, V) float32 via radix select in
    sortable bit space: 8 passes of 4 bits, each counting elements >= 16
    candidate thresholds with a fused compare+reduce. Replaces
    ``lax.top_k`` for the top-k *filter*, where only the k-th value is
    needed: XLA lowers top_k to a full (B, V) sort, measured 377 us per
    decode step at B=1/V=50304 on v5e vs ~20 us for this select (the
    sort was 44% of the 124M decode step). ``k`` is a python int or a
    (B,) int32 array of per-row ranks (the serving engine's per-slot
    top-k) — k only ever feeds the counts comparison, so the select is
    rank-vectorized for free. Returns (B,) float32."""
    u = _sortable_f32(logits)
    B = logits.shape[0]
    k_col = jnp.broadcast_to(jnp.asarray(k, jnp.int32), (B,))[:, None]
    lo = jnp.zeros((B,), jnp.uint32)
    for shift in range(28, -1, -4):
        cand = (lo[:, None]
                + (jnp.arange(16, dtype=jnp.uint32)[None, :] << shift))
        counts = jnp.sum((u[:, :, None] >= cand[:, None, :])
                         .astype(jnp.int32), axis=1)
        # candidates are ascending, so counts are non-increasing: the
        # chosen bucket is the largest whose count still reaches k.
        # count(u >= lo) >= k holds at every pass (lo starts at 0 and
        # only advances to satisfying prefixes), so sel >= 0 always.
        sel = jnp.sum((counts >= k_col).astype(jnp.int32), axis=1) - 1
        lo = lo + (sel.astype(jnp.uint32) << shift)
    return _unsortable_f32(lo)


def _top_k_filter(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask logits strictly below the k-th largest to -inf — the
    reference's filter semantics (``logits < v[:, [-1]]``,
    /root/reference/GPT-2.py:245-247; ties at the k-th value are kept).
    Bit-identical to the ``lax.top_k`` formulation (asserted in
    tests/test_generate.py), without the full-vocab sort. Small vocabs
    keep the sort: the radix select's 8 fixed passes only pay off once
    the sort is the bigger cost (char-GPT's V=65 sort is trivial; the
    win is GPT-2's V=50257)."""
    if logits.dtype != jnp.float32 or logits.shape[-1] < 1024:
        kth = jax.lax.top_k(logits, k)[0][:, -1:]
        return jnp.where(logits < kth, -jnp.inf, logits)
    t = _kth_largest(logits, k)
    return jnp.where(logits < t[:, None], -jnp.inf, logits)


def _top_p_filter(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filter: keep the smallest prefix of the descending-softmax
    distribution whose cumulative probability reaches ``p`` (always
    including the top token), mask the rest to -inf. Sort-based, O(V log V)
    on device — static shapes, jit/scan-friendly.

    Rank-based (keep flags scattered back through the argsort), not
    value-thresholded: boundary ties cannot widen the nucleus past the
    prefix (a value threshold would keep every token tied with the
    boundary logit — a no-op on fully tied rows)."""
    idx = jnp.argsort(logits, axis=-1)[:, ::-1]          # descending order
    sorted_logits = jnp.take_along_axis(logits, idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # sorted position i is kept iff the cumulative mass BEFORE it is < p
    # (so the top token is always kept and the prefix first reaches >= p)
    keep = (cum - probs) < p
    rows = jnp.arange(logits.shape[0])[:, None]
    mask = jnp.zeros(logits.shape, bool).at[rows, idx].set(keep)
    return jnp.where(mask, logits, -jnp.inf)


def _sample_token(rng: jax.Array, logits: jnp.ndarray,
                  gcfg: GenerateConfig) -> jnp.ndarray:
    """logits: (B, V) float32 -> (B,) int32."""
    if gcfg.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(gcfg.temperature, 1e-6)
    if gcfg.top_k and gcfg.top_k > 0:
        k = min(gcfg.top_k, logits.shape[-1])
        logits = _top_k_filter(logits, k)
    if gcfg.top_p and gcfg.top_p > 0.0:
        logits = _top_p_filter(logits, gcfg.top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Batched per-row sampling (the continuous-batching engine's sampler:
# every row is a pool slot with its OWN temperature/top-k/top-p/greedy
# and its own rng stream — same filter math as the scalar path above,
# vectorized over rows with per-row off-switches)
# ---------------------------------------------------------------------------

def batched_top_k_filter(logits: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Per-row top-k filter: k is (B,) int32; rows with k <= 0 or
    k >= V pass through UNCHANGED (bit-exact off-switch — not a k=V
    filter, which would still mask zero-probability ties differently).
    Same kept-set semantics as ``_top_k_filter`` (ties at the k-th value
    kept), via the radix select (``_kth_largest`` takes per-row k: it
    only ever compares counts >= k)."""
    V = logits.shape[-1]
    k = jnp.asarray(k, jnp.int32)
    off = (k <= 0) | (k >= V)
    k_eff = jnp.where(off, 1, k)  # any valid k; rows masked back below
    t = _kth_largest(logits.astype(jnp.float32), k_eff)
    filtered = jnp.where(logits < t[:, None], -jnp.inf, logits)
    return jnp.where(off[:, None], logits, filtered)


def batched_top_p_filter(logits: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Per-row nucleus filter: p is (B,) float32; rows with p <= 0 or
    p >= 1 pass through unchanged. Same rank-based prefix semantics as
    ``_top_p_filter``."""
    p = jnp.asarray(p, jnp.float32)
    off = (p <= 0.0) | (p >= 1.0)
    idx = jnp.argsort(logits, axis=-1)[:, ::-1]
    sorted_logits = jnp.take_along_axis(logits, idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < jnp.where(off, 1.0, p)[:, None]
    rows = jnp.arange(logits.shape[0])[:, None]
    mask = jnp.zeros(logits.shape, bool).at[rows, idx].set(keep)
    filtered = jnp.where(mask, logits, -jnp.inf)
    return jnp.where(off[:, None], logits, filtered)


def filter_logits_batched(logits: jnp.ndarray, temperature: jnp.ndarray,
                          top_k: jnp.ndarray, top_p: jnp.ndarray
                          ) -> jnp.ndarray:
    """The per-row stochastic filter pipeline — temperature -> top-k ->
    top-p, each (B,)-parameterized — factored out of
    ``sample_tokens_batched`` so the speculative verifier
    (serve/speculative.py) scores drafted tokens against EXACTLY the
    distribution the engine would have sampled from (rejection sampling
    is only target-preserving if both sides use the same filters)."""
    scaled = logits / jnp.maximum(
        jnp.asarray(temperature, jnp.float32), 1e-6)[:, None]
    f = batched_top_k_filter(scaled, top_k)
    return batched_top_p_filter(f, top_p)


def sample_tokens_batched(rngs: jnp.ndarray, logits: jnp.ndarray,
                          temperature: jnp.ndarray, top_k: jnp.ndarray,
                          top_p: jnp.ndarray, greedy: jnp.ndarray
                          ) -> jnp.ndarray:
    """Per-row sampling: (B,) params, (B, key) rngs, (B, V) f32 logits
    -> (B,) int32. Greedy rows take argmax of the RAW logits (exactly
    ``_sample_token``'s greedy mode, so a greedy slot in a mixed batch
    is token-identical to a scalar greedy decode); stochastic rows get
    temperature -> top-k -> top-p, each per-row, then a per-row
    categorical draw from the row's own key."""
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    f = filter_logits_batched(logits, temperature, top_k, top_p)
    sampled = jax.vmap(jax.random.categorical)(rngs, f).astype(jnp.int32)
    return jnp.where(jnp.asarray(greedy, bool), greedy_tok, sampled)


def _decode_chunks(P_pad: int, n_new: int, S: int, g: int):
    """Static (n_steps, cache_len) chunks covering an ``n_new``-step
    decode scan whose step i writes position <= P_pad - 1 + i. The KV
    cache buffer starts at the first chunk's cache_len (a multiple of
    the granule ``g``, capped at S) and is zero-padded up between chunks,
    so early steps stop paying for the whole static bucket — at B >= 8
    the cache read dominates decode step bytes and a 1k-token sample
    from a short prompt otherwise streams all S slots from token 1
    (measured 2.8-3.0x above the full-cache byte floor at 124M; the
    chunked scan reads ~0.56x the bytes on that workload). Growing the
    *buffer* keeps the in-chunk loop byte-identical to the plain
    fixed-bucket scan — a static prefix slice of the carried buffer
    instead was measured 10x worse (see models.gpt.decode_step). All
    chunks compile into the ONE jitted segment — more scan bodies, zero
    extra dispatches."""
    if n_new <= 0:
        # one zero-step chunk: callers still get a valid cache bound
        return [(0, min(-(-P_pad // g) * g, S))]
    chunks = []
    i = 0
    while i < n_new:
        a = min(-(-(P_pad + i) // g) * g, S)
        n_c = n_new - i if a >= S else min(n_new - i, a - (P_pad - 1) - i)
        chunks.append((n_c, a))
        i += n_c
    return chunks


def _segment_core(params, prompt: jnp.ndarray, prompt_len, n_new: int,
                  rng: jax.Array, cfg: ModelConfig, gcfg: GenerateConfig,
                  allow_pallas: bool = False) -> jnp.ndarray:
    """One prefill + decode scan: fill the KV cache for the whole padded
    prompt in ONE parallel forward (``models.gpt.prefill`` — the previous
    formulation teacher-forced the prompt through ``P_pad - 1``
    sequential decode steps, ~43% of all steps on the 1k-token char
    workload), then run exactly ``n_new`` sampling steps starting at
    position ``prompt_len - 1``. ``prompt_len`` is a TRACED scalar — the
    prompt array may be right-padded to a bucketed width, so true length
    does not force a recompile; padding-derived cache entries at
    positions >= prompt_len are overwritten before being attended.
    Requires P_pad + n_new <= block_size + 1.

    The scan is split into ``_decode_chunks`` with a cache buffer grown
    chunk-by-chunk (see there); the rng-split sequence per step is
    unchanged and the padded slots are masked exactly like unfilled
    bucket slots, so the sampled trajectory matches a single full-bucket
    scan (asserted in tests/test_generate.py)."""
    B, P_pad = prompt.shape
    chunks = _decode_chunks(P_pad, n_new, cfg.block_size,
                            gcfg.attend_granule)
    cache = init_kv_cache(cfg, B, max_len=chunks[0][1])
    prompt_len = jnp.asarray(prompt_len, jnp.int32)
    cache = prefill(params, prompt, cache, cfg)
    start = prompt_len - 1
    first = jax.lax.dynamic_slice_in_dim(prompt, start, 1, axis=1)[:, 0]

    def body(carry, i):
        tok, cache, rng = carry
        logits, cache = decode_step(params, tok, start + i, cache, cfg,
                                    allow_pallas=allow_pallas)
        rng, sub = jax.random.split(rng)
        next_tok = _sample_token(sub, logits, gcfg)
        return (next_tok, cache, rng), next_tok

    carry = (first, cache, rng)
    parts = []
    i = 0
    seq_ax = cache_seq_axis(cfg)  # layout-dependent (packed vs heads)
    for n_c, a_len in chunks:
        tok, cache, crng = carry
        if cache["k"].shape[seq_ax] < a_len:
            grow = a_len - cache["k"].shape[seq_ax]
            pad = [(0, 0)] * cache["k"].ndim
            pad[seq_ax] = (0, grow)
            cache = {key: jnp.pad(val, pad) for key, val in cache.items()}
        carry, toks_c = jax.lax.scan(body, (tok, cache, crng),
                                     jnp.arange(i, i + n_c))
        parts.append(toks_c)
        i += n_c
    toks = (parts[0] if len(parts) == 1
            else jnp.concatenate(parts, axis=0))
    return toks.T


@partial(jax.jit, static_argnames=("n_new", "cfg", "gcfg", "allow_pallas"))
def _decode_segment(params, prompt: jnp.ndarray, prompt_len, n_new: int,
                    rng: jax.Array, cfg: ModelConfig, gcfg: GenerateConfig,
                    allow_pallas: bool = False) -> jnp.ndarray:
    """Jitted ``_segment_core`` — compiled shapes are keyed on
    (P_pad, n_new) buckets only (plus the static allow_pallas kernel
    gate); see ``generate`` for the bucketing."""
    return _segment_core(params, prompt, prompt_len, n_new, rng, cfg, gcfg,
                         allow_pallas)


@partial(jax.jit, static_argnames=("n_seg", "cfg", "gcfg", "allow_pallas"))
def _refresh_group(params, window: jnp.ndarray, n_seg: int, first_ord,
                   base_rng: jax.Array, cfg: ModelConfig,
                   gcfg: GenerateConfig, allow_pallas: bool = False):
    """``n_seg`` window-refresh segments in ONE dispatch: an on-device
    ``lax.scan`` whose body is a full segment (prefill the (B, S//2)
    window, sample S//2 + 1 tokens, slide the window). The host loop
    used one dispatch per segment, so a 1k-token char-GPT sample paid
    ~7 sequential tunnel round trips; ``generate`` now dispatches
    power-of-two group sizes from the binary decomposition of the
    segment count — popcount(k) dispatches, a bounded compile set
    (one program per power of two), zero wasted decode steps. Segment
    rngs derive from ``fold_in(base_rng, segment ordinal)`` so the
    sampled stream is invariant to how segments are grouped (a
    sequential split chain would make tokens depend on max_new_tokens
    through the decomposition). Returns ((B, n_seg * (S//2+1)) tokens,
    the final (B, S//2) window)."""
    S = cfg.block_size
    Pw, n_mid = S // 2, S // 2 + 1

    def seg(window, i):
        sub = jax.random.fold_in(base_rng, first_ord + i)
        toks = _segment_core(params, window, Pw, n_mid, sub, cfg, gcfg,
                             allow_pallas)
        window = jnp.concatenate([window, toks], axis=1)[:, -Pw:]
        return window, toks

    window, toks = jax.lax.scan(seg, window, jnp.arange(n_seg))
    B = window.shape[0]
    return jnp.moveaxis(toks, 0, 1).reshape(B, n_seg * n_mid), window


def _pow2_at_least(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


def shard_for_decode(params, prompt: jnp.ndarray, cfg: ModelConfig,
                     mesh, mesh_cfg):
    """Lay out params and prompt for sharded decoding on ``mesh``.

    Decode-time layout differs from training: params use the Megatron TP
    specs over 'model' but replicate over 'data' (FSDP's gather-per-use
    trades latency for memory in exactly the wrong direction for
    single-token steps) and the pipe axis is ignored (no microbatching at
    decode). The prompt batch shards over 'data' when divisible, else
    replicates. The KV cache needs no explicit spec: it is created inside
    the jitted segment from TP-sharded k/v projections, so GSPMD
    propagates the head sharding to it.

    The result feeds straight into ``generate`` — the same jitted
    ``_decode_segment`` runs sharded, with XLA inserting the TP
    collectives (psum after row-parallel projections, gather for the
    sharded-vocab logits at the sampling step).
    """
    import dataclasses as _dc

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import param_pspecs

    decode_cfg = _dc.replace(mesh_cfg, fsdp=False, pipe=1)
    specs = param_pspecs(cfg, decode_cfg)
    params = jax.device_put(params, jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs))
    B = prompt.shape[0]
    bspec = P("data") if B % mesh_cfg.data == 0 else P(None)
    prompt = jax.device_put(jnp.asarray(prompt, jnp.int32),
                            NamedSharding(mesh, P(*bspec, None)))
    return params, prompt


def generate(params, prompt: jnp.ndarray, cfg: ModelConfig,
             gcfg: GenerateConfig = GenerateConfig(),
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Generate ``gcfg.max_new_tokens`` continuations of ``prompt``.

    prompt: (B, P) int32, 1 <= P <= block_size (the reference's "zero
    context" start, GPT1.py:235, is a single 0 token). Returns
    (B, max_new_tokens) int32.

    Sharded decoding: pass params/prompt through ``shard_for_decode``
    first; everything below is sharding-agnostic (jit + GSPMD propagate
    the layouts through the scan).

    Compile stability: segment shapes are bucketed so a long sample costs
    a fixed small set of XLA programs instead of one per segment —
    (a) the prompt is right-padded to a power-of-two width with the true
    length passed traced, (b) the first segment's decode count rounds up
    to a power of two (capped by cache room), and (c) every window-refresh
    segment uses the single shape (block_size//2, block_size//2 + 1), with
    the final segment's surplus tokens truncated (surplus decode steps are
    bounded by block_size//2 per sample — cheap next to a recompile).
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    prompt = jnp.asarray(prompt, jnp.int32)
    assert prompt.ndim == 2 and prompt.shape[1] >= 1
    assert prompt.shape[1] <= cfg.block_size, "prompt longer than block_size"
    assert gcfg.attend_granule >= 1, "attend_granule must be >= 1"
    S = cfg.block_size
    B, P0 = prompt.shape
    chunks = []
    remaining = gcfg.max_new_tokens
    if remaining <= 0:
        return jnp.zeros((B, 0), jnp.int32)
    # gcfg is a static jit arg of _decode_segment; normalize the length
    # field out of it so requesting a different max_new_tokens cannot
    # recompile the segments (only sampling params belong in the key)
    import dataclasses as _dc
    gcfg = _dc.replace(gcfg, max_new_tokens=0)

    # decode kernels (fused / packed attention) only where GSPMD cannot
    # shard the segment — decided on the REAL params, outside jit
    allow_pallas = _all_single_device(params) and _all_single_device(prompt)

    # first segment: bucketed prompt pad + bucketed decode count
    P_pad = min(_pow2_at_least(P0), S)
    padded = (prompt if P_pad == P0 else jnp.pad(
        prompt, ((0, 0), (0, P_pad - P0))))
    room = S - P_pad + 1
    n1 = min(_pow2_at_least(remaining), room)
    rng, sub = jax.random.split(rng)
    toks = _decode_segment(params, padded, P0, n1, sub, cfg, gcfg,
                           allow_pallas)
    take = min(n1, remaining)
    chunks.append(toks[:, :take])
    remaining -= take
    window = jnp.concatenate([prompt, toks[:, :take]], axis=1)

    # refresh segments: one fixed shape (S//2 prompt, S//2+1 new),
    # dispatched in power-of-two groups (binary decomposition of the
    # segment count — popcount(k) dispatches instead of k, final
    # surplus tokens truncated as before)
    Pw, n_mid = S // 2, S // 2 + 1
    if remaining > 0:
        window = window[:, -Pw:]
        # only entered after a full first segment, which always leaves
        # P0 + (S - P_pad + 1) > Pw true tokens — padding here would
        # teacher-force fabricated context, so fail loudly instead
        assert window.shape[1] == Pw, window.shape
        # every refresh segment's rng is fold_in(base, ordinal) — the
        # stream does not depend on batch gate or group decomposition
        rng, base = jax.random.split(rng)
        ordinal = 0
        if B < 16:
            # grouped dispatch pays when per-step device time is small
            # relative to the per-dispatch overhead (measured on v5e
            # char-GPT 1k tokens: B=1 166-204 -> 129-153 ms, B=8
            # 201-247 -> 168-176; at B=32 device time dominates and the
            # scan costs ~7% — the per-segment loop keeps it)
            k = -(-remaining // n_mid)
            g = 1 << (k.bit_length() - 1)
            while k > 0:
                if g <= k:
                    # key+counter idiom: _refresh_group fold_ins the
                    # per-segment ordinal internally, so passing `base`
                    # each iteration is NOT stream reuse (see the
                    # fold_in(base, ordinal) comment above)
                    toks, window = _refresh_group(  # graftlint: disable=GL003
                        params, window, g, jnp.int32(ordinal), base,
                        cfg, gcfg, allow_pallas)
                    take = min(g * n_mid, remaining)
                    chunks.append(toks[:, :take])
                    remaining -= take
                    ordinal += g
                    k -= g
                g //= 2
        else:
            while remaining > 0:
                sub = jax.random.fold_in(base, ordinal)
                toks = _decode_segment(params, window, Pw, n_mid, sub, cfg,
                                       gcfg, allow_pallas)
                take = min(n_mid, remaining)
                chunks.append(toks[:, :take])
                remaining -= take
                ordinal += 1
                window = jnp.concatenate([window, toks[:, :take]],
                                         axis=1)[:, -Pw:]
    return jnp.concatenate(chunks, axis=1)
