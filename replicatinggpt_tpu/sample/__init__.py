from .generate import generate, GenerateConfig, shard_for_decode

__all__ = ["generate", "GenerateConfig", "shard_for_decode"]
