from .generate import generate, GenerateConfig

__all__ = ["generate", "GenerateConfig"]
