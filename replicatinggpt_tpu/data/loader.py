"""Batchers + async device prefetch.

Two sampling disciplines, matching the reference's two loaders:

- :class:`RandomBatcher` — uniform random windows, fresh each step
  (``get_batch``, GPT1.py:75-83).
- :class:`SequentialBatcher` — contiguous ``B*T+1`` windows with wraparound
  and a persistent cursor (``DataLoaderLite``, GPT-2.py:187-213). The cursor
  is exposed as checkpointable state (the reference lost it on crash).

Both yield ``(x, y)`` NumPy int32 arrays of shape (B, T) with y = x shifted
by one. :func:`prefetch` overlaps host batch assembly + H2D transfer with
device compute (the reference's per-step synchronous ``.to(device)`` at
GPT1.py:81 is exactly the bubble this removes).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Tuple

import numpy as np

Batch = Tuple[np.ndarray, np.ndarray]


class RandomBatcher:
    """Uniform random (B, T) windows — GPT1.py:75-83 semantics."""

    def __init__(self, data: np.ndarray, batch_size: int, block_size: int,
                 seed: int = 0):
        assert len(data) > block_size + 1, "corpus shorter than block_size"
        self.data = np.ascontiguousarray(data, np.int32)
        self.B, self.T = batch_size, block_size
        self.rng = np.random.default_rng(seed)

    def next_batch(self) -> Batch:
        # exclusive high len-T: max start len-T-1, so y = data[i+1 : i+T+1]
        # still fits (same bound as the reference's randint, GPT1.py:77)
        ix = self.rng.integers(0, len(self.data) - self.T, size=self.B)
        # fused native gather (C++), NumPy fallback inside — batch content
        # is a pure function of (data, ix) either way, so the seeded token
        # stream is independent of which path runs
        from ..native import gather_batch
        return gather_batch(self.data, ix, self.T)

    def __iter__(self) -> Iterator[Batch]:
        while True:
            yield self.next_batch()

    # random sampling has no meaningful cursor; RNG state is the resume state
    def state(self) -> dict:
        return {"bit_generator": self.rng.bit_generator.state}

    def restore(self, state: dict) -> None:
        self.rng.bit_generator.state = state["bit_generator"]


class SequentialBatcher:
    """Contiguous windows with wraparound cursor — GPT-2.py:200-213 semantics.

    ``shard=(i, n)`` makes this one of n multi-host shards: the cursor walks
    *global* (n*B*T)-token windows and this instance materializes only its
    i-th contiguous B*T slice, so the assembled global batch is the same
    token stream a single-host run would see. The cursor is identical on
    every shard (it is global state), which keeps checkpoint save/restore
    host-count independent.
    """

    def __init__(self, data: np.ndarray, batch_size: int, block_size: int,
                 shard: Tuple[int, int] = (0, 1)):
        self.shard_index, self.num_shards = shard
        need = self.num_shards * batch_size * block_size + 1
        assert len(data) >= need, (
            f"corpus of {len(data)} tokens cannot fill one {need}-token window")
        self.data = data
        self.B, self.T = batch_size, block_size
        self.position = 0

    def next_batch(self) -> Batch:
        B, T = self.B, self.T
        stride = self.num_shards * B * T
        if self.position + stride + 1 > len(self.data):
            self.position = 0
        start = self.position + self.shard_index * B * T
        buf = self.data[start:start + B * T + 1]
        x = buf[:-1].reshape(B, T)
        y = buf[1:].reshape(B, T)
        self.position += stride
        return x.astype(np.int32), y.astype(np.int32)

    def __iter__(self) -> Iterator[Batch]:
        while True:
            yield self.next_batch()

    def state(self) -> dict:
        return {"position": self.position}

    def restore(self, state: dict) -> None:
        self.position = int(state["position"])


def make_batcher(kind: str, data: np.ndarray, batch_size: int,
                 block_size: int, seed: int = 0,
                 shard: Tuple[int, int] = (0, 1)):
    if kind == "random":
        return RandomBatcher(data, batch_size, block_size, seed)
    if kind == "sequential":
        return SequentialBatcher(data, batch_size, block_size, shard=shard)
    raise ValueError(f"unknown sampling kind {kind!r}")


def prefetch(batches: Iterator[Batch], sharding=None,
             depth: int = 2) -> Iterator:
    """Move batches to device on a background thread, ``depth`` ahead.

    ``sharding`` is an optional ``jax.sharding.Sharding`` for the global
    (B, T) batch (data/seq-parallel layouts); None keeps the default single
    -device placement. Stacked items of any rank — (K, B, T) multi-step
    superbatches, (accum, B, T) gradient-accumulation stacks, or
    (K, accum, B, T) when the two compose — derive their layout from
    ``sharding``: every leading stack dim replicates, the trailing (B, T)
    keep the batch spec (so no dispatch shape ever drops the batch
    sharding).
    """
    import jax

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def _put(item) -> bool:
        # bounded put that re-checks stop, so a full queue can't strand the
        # producer thread (and its device-resident batches) after the
        # consumer stops early
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _place(a):
        if not isinstance(a, np.ndarray):
            return a  # pass-through metadata (e.g. batcher-cursor snapshots)
        if sharding is None:
            return jax.device_put(a)
        # multi-process: each host contributes only its local rows
        # (jax.make_array_from_process_local_data); single-process
        # this is plain device_put with the sharding
        from ..parallel.distributed import global_batch
        if a.ndim > 2:
            # stacked items: leading dims replicate, (B, T) keeps the
            # batch spec — derived from the base batch sharding
            from jax.sharding import NamedSharding, PartitionSpec
            spec = PartitionSpec(*([None] * (a.ndim - 2)),
                                 *sharding.spec)
            stacked = NamedSharding(sharding.mesh, spec)
            return global_batch(a, stacked, batch_axis=a.ndim - 2)
        return global_batch(a, sharding)

    def producer():
        try:
            for b in batches:
                if stop.is_set():
                    return
                b = tuple(_place(a) for a in b)
                if not _put(b):
                    return
            _put(None)
        except BaseException as e:  # noqa: BLE001 — surface in the consumer
            # a dead producer must not leave the consumer blocked on q.get()
            _put(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            b = q.get()
            if b is None:
                return
            if isinstance(b, BaseException):
                raise b
            yield b
    finally:
        stop.set()
        while not q.empty():  # release device references promptly
            try:
                q.get_nowait()
            except queue.Empty:
                break
