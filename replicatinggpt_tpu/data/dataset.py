"""Corpus loading and tokenized train/val splits.

Replaces the reference's import-time corpus handling (GPT1.py:25-70): read
text, tokenize once, 90/10 split. Tokens are held host-side as a NumPy array;
device placement happens in the batcher/prefetcher (the reference instead did
a synchronous ``.to(device)`` per step, GPT1.py:81).
"""

from __future__ import annotations

import dataclasses

import numpy as np


def load_corpus(path: str) -> str:
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


@dataclasses.dataclass
class TokenDataset:
    """Tokenized corpus with a train/val split (GPT1.py:68-70 semantics)."""

    train: np.ndarray  # int32 [n_train]
    val: np.ndarray    # int32 [n_val]
    vocab_size: int

    @classmethod
    def from_text(cls, text: str, tokenizer, val_fraction: float = 0.1
                  ) -> "TokenDataset":
        if hasattr(tokenizer, "encode_np"):  # native fastpath when built
            ids = np.asarray(tokenizer.encode_np(text), dtype=np.int32)
        else:
            ids = np.asarray(tokenizer.encode(text), dtype=np.int32)
        n = int(len(ids) * (1.0 - val_fraction))
        return cls(train=ids[:n], val=ids[n:], vocab_size=tokenizer.vocab_size)

    def split(self, name: str) -> np.ndarray:
        assert name in ("train", "val"), name
        return self.train if name == "train" else self.val
