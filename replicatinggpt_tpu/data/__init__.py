from .dataset import TokenDataset, load_corpus
from .loader import RandomBatcher, SequentialBatcher, make_batcher, prefetch

__all__ = [
    "TokenDataset", "load_corpus", "RandomBatcher", "SequentialBatcher",
    "make_batcher", "prefetch",
]
