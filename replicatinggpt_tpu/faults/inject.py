"""Deterministic fault injection behind no-op-by-default seams.

The supervision layer (faults/supervise.py, faults/watchdog.py) claims
to survive preemption, transient I/O faults, silent corruption, and
numerical blowups — claims that are untestable without a way to *cause*
those faults on demand. This module is that way: a :class:`FaultPlan`
describes exactly which fault fires at which site and step, and the
seams compiled into the hot paths (`train/runner.py`,
`train/checkpoint.py`, `serve/engine.py`, `serve/speculative.py`) ask
it, via :func:`fire`, whether to misbehave.

Design constraints, in order:

1. **No-op by default.** With no plan installed, a seam is one module
   attribute read and a ``None`` comparison — nothing on the device,
   nothing allocated, no branch the jit ever sees (every seam runs in
   host code between dispatches).
2. **Deterministic.** Faults trigger on explicit per-site indices (the
   engine passes its step counter, the checkpoint manager the step id)
   or on the seam's own call counter — never on wall-clock races. Each
   fault fires at most ``times`` times across the whole plan lifetime,
   so a rolled-back training run that replays the faulted step does
   NOT re-trip a one-shot fault (exactly how a transient fault behaves
   in production, and what the bitwise-resume chaos tests rely on).
   Corruption payloads draw from a ``seed``-keyed RNG.
3. **Injected faults are indistinguishable from real ones.** The
   checkpoint corruptor flips bytes in the files orbax actually wrote;
   the transient-I/O fault raises a plain ``OSError``; the SIGTERM
   fault raises the real signal through the real handler. Recovery
   code cannot special-case "test mode" because there is none.

Sites and kinds (the fault matrix — docs/robustness.md):

========================  ==========  =======================================
site                      kind        effect at the seam
========================  ==========  =======================================
``ckpt/save``             ``io``      transient ``OSError`` before the write
``ckpt/restore``          ``io``      transient ``OSError`` before the read
``ckpt/finalize``         ``corrupt``   flip bytes in the step's largest file
``ckpt/finalize``         ``truncate``  truncate it to half (partial write)
``ckpt/finalize``         ``drop_manifest``  delete the integrity manifest
``train/step``            ``sigterm``   raise SIGTERM (preemption notice)
``train/step``            ``nan_params``  scale one param leaf by NaN
``train/loss``            ``nan``     observed loss becomes NaN
``train/loss``            ``spike``   observed loss scaled by ``arg``
``serve/step``            ``delay``   ``time.sleep(arg)`` before the dispatch
``spec/draft``            ``collapse``  shift every drafted token by one
``fleet/step``            ``replica_kill``  router abandons replica ``arg``
``fleet/step``            ``replica_wedge`` replica ``arg2`` steps stall
                                      ``arg`` seconds (partition stand-in)
``fleet/session``         ``hot_key_skew``  loadgen collapses sessions onto
                                      one prefix group w.p. ``arg``
========================  ==========  =======================================

The ``fleet/*`` sites live behind :mod:`faults.fleet` (the router and
load generator consult them); they reuse this module's machinery
unchanged — same determinism, same one-shot counting, same no-op
default.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class Fault:
    """One planned fault.

    Fires when its ``site`` seam is hit with an index in
    ``[at, at + times)`` — where the index is the seam's explicit
    counter (engine step, checkpoint step) when it passes one, else the
    seam's own call count — AND the fault has fired fewer than
    ``times`` times in total. The total-count cap is what makes a
    step-indexed fault one-shot across a rollback replay of the same
    step. ``after_s`` (optional) additionally delays eligibility until
    that many seconds after plan installation.
    """

    site: str
    kind: str
    at: int = 0
    times: int = 1
    arg: float = 0.0
    arg2: float = 0.0      # second payload (fleet faults: replica index)
    after_s: float = 0.0


class FaultPlan:
    """An installed set of faults plus the bookkeeping that makes them
    deterministic: per-site call counters, per-fault fire counts, and a
    ``fired`` log the chaos tests assert against."""

    def __init__(self, *faults: Fault, seed: int = 0):
        self.faults: Tuple[Fault, ...] = faults
        self.seed = seed
        self._counts: Dict[str, int] = {}
        self._fired_counts: Dict[int, int] = {}
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        #: (site, kind, index) log of every firing, in order
        self.fired: List[Tuple[str, str, int]] = []

    def fire(self, site: str, index: Optional[int] = None) -> Optional[Fault]:
        with self._lock:
            n = self._counts.get(site, 0)
            self._counts[site] = n + 1
            idx = n if index is None else index
            now = time.monotonic()
            for i, f in enumerate(self.faults):
                if f.site != site:
                    continue
                if not (f.at <= idx < f.at + f.times):
                    continue
                if self._fired_counts.get(i, 0) >= f.times:
                    continue
                if f.after_s and now - self._t0 < f.after_s:
                    continue
                self._fired_counts[i] = self._fired_counts.get(i, 0) + 1
                self.fired.append((site, f.kind, idx))
                return f
            return None

    def rng(self, site: str) -> np.random.Generator:
        """Seeded payload RNG, stable per (plan seed, site)."""
        return np.random.default_rng(
            [self.seed, sum(site.encode())])

    def count(self, site: str, kind: Optional[str] = None) -> int:
        return sum(1 for s, k, _ in self.fired
                   if s == site and (kind is None or k == kind))


_PLAN: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    global _PLAN
    _PLAN = plan
    return plan


def clear() -> None:
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


@contextlib.contextmanager
def installed(plan: FaultPlan):
    """``with installed(FaultPlan(...)) as plan:`` — guaranteed cleanup
    so a failing chaos test can't leak faults into the next one."""
    install(plan)
    try:
        yield plan
    finally:
        clear()


def fire(site: str, index: Optional[int] = None) -> Optional[Fault]:
    """The seam entry point: None (almost always) or the fault to apply.

    The no-plan fast path is a single module-global read — cheap enough
    to sit inside the train loop and the serve engine's step."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.fire(site, index)


# ---------------------------------------------------------------------------
# payload helpers — the code that actually breaks things
# ---------------------------------------------------------------------------

def corrupt_step_dir(directory: str, step: int, kind: str,
                     rng: np.random.Generator) -> str:
    """Corrupt a finalized checkpoint step on disk, the way real bit rot
    or a partial write would: ``corrupt`` flips bytes at seeded offsets
    in the step's largest file (silent corruption — only a checksum can
    see it); ``truncate`` cuts that file to half (a crash mid-write).
    Returns the path touched. Raises FileNotFoundError if the step dir
    has no files (the save must be finalized before corrupting it)."""
    step_dir = os.path.join(directory, str(step))
    candidates = []
    for root, _, files in os.walk(step_dir):
        for name in files:
            p = os.path.join(root, name)
            sz = os.path.getsize(p)
            if sz > 0:
                candidates.append((sz, p))
    if not candidates:
        raise FileNotFoundError(f"no files under {step_dir} to corrupt")
    _, target = max(candidates)
    size = os.path.getsize(target)
    if kind == "truncate":
        with open(target, "r+b") as f:
            f.truncate(max(size // 2, 1))
    else:
        offsets = rng.integers(0, size, size=min(8, size))
        with open(target, "r+b") as f:
            for off in offsets:
                f.seek(int(off))
                b = f.read(1)
                f.seek(int(off))
                f.write(bytes([b[0] ^ 0xFF]))
    return target


def apply_train_state_fault(fault: Fault, state):
    """Apply a ``train/step`` fault to the live train state (host side,
    between dispatches). ``sigterm`` raises the real signal — the CLI's
    installed handler turns it into a graceful checkpoint-and-stop,
    exactly the preemption path. ``nan_params`` scales the first
    parameter leaf by NaN: the next forward produces a non-finite loss,
    which is the supervisor's job to catch and roll back."""
    if fault.kind == "sigterm":
        import signal
        signal.raise_signal(signal.SIGTERM)
        return state
    if fault.kind == "nan_params":
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(state.params)
        # eager scalar multiply keeps shape/dtype/placement — the guarded
        # train-step jit sees identical avals and does not recompile
        leaves[0] = leaves[0] * float("nan")
        return state._replace(
            params=jax.tree_util.tree_unflatten(treedef, leaves))
    raise ValueError(f"unknown train/step fault kind {fault.kind!r}")


def apply_loss_fault(fault: Fault, loss: float) -> float:
    """Apply a ``train/loss`` fault to the observed (host) loss value."""
    if fault.kind == "nan":
        return float("nan")
    if fault.kind == "spike":
        return loss * (fault.arg or 100.0)
    raise ValueError(f"unknown train/loss fault kind {fault.kind!r}")
