"""Train self-healing: loss-spike/NaN supervision with checkpoint rollback.

PR 2 gave the train loop *detection* (sanitize-mode NaN checks kill the
run at train/runner.py's loss fetch) and the checkpoint layer gives it
*durability*; this module adds *recovery* — the piece a long preemptible
run actually needs. :func:`supervised_train` wraps ``train.runner.train``
in the standard production recipe:

1. the runner (under a :class:`SupervisionConfig`) fetches the loss once
   per ``check_every`` dispatches and raises a typed error on a
   non-finite value (:class:`NonFiniteLossError`) or on a spike past
   ``spike_factor`` x the running EMA (:class:`LossSpikeError`);
2. the supervisor catches it, counts a ``rollback``, and re-enters
   training with ``resume=True`` — restore_latest lands on the newest
   checkpoint that passes integrity verification (a NaN-poisoned or
   corrupt save is skipped, counted as ``ckpt_fallbacks``);
3. a *repeat* failure at the same step means the fault is in the data
   window, not transient — the supervisor advances the data cursor
   ``skip_window`` optimizer steps past it (``data_skips``) before the
   next attempt;
4. after ``max_rollbacks`` failed recoveries it re-raises as
   :class:`SupervisionExhausted` — dying is correct once recovery
   demonstrably doesn't work.

A transient fault (a one-shot blowup) therefore resumes **bitwise
identical** to an uninterrupted run: the rollback restores the exact
state + data cursor, replay re-consumes the same token stream, and the
step-keyed dropout RNG makes the tail deterministic — pinned by
tests/test_faults.py.

All recovery actions land in a ``utils.logging.Metrics`` instance
(``rollbacks``, ``data_skips``, plus the checkpoint manager's
``ckpt_fallbacks`` / ``save_retries`` / ``restore_retries``) so the
bench artifacts can report them.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..utils.logging import Metrics, StepLogger
from ..utils.telemetry import NULL


class NonFiniteLossError(FloatingPointError):
    """Training loss went NaN/inf at ``step`` (subclasses
    FloatingPointError so GRAFT_SANITIZE handlers keep working)."""

    def __init__(self, step: int, loss: float):
        super().__init__(f"train loss at step {step} is {loss} — "
                         f"non-finite")
        self.step = step
        self.loss = loss


class LossSpikeError(RuntimeError):
    """Training loss spiked past the supervisor's budget at ``step``."""

    def __init__(self, step: int, loss: float, ema: float, factor: float):
        super().__init__(
            f"train loss at step {step} spiked to {loss:.4f} "
            f"(> {factor:.1f} x running mean {ema:.4f})")
        self.step = step
        self.loss = loss
        self.ema = ema


class SupervisionExhausted(RuntimeError):
    """Recovery failed ``max_rollbacks`` times — the run is dead."""


@dataclass(frozen=True)
class SupervisionConfig:
    """Runner-side detection knobs (the runner only *detects*; the
    supervisor recovers). ``check_every`` is in dispatches — each check
    is one host sync, the documented cost of supervision. ``spike_factor``
    0 disables spike detection (non-finite always raises)."""

    check_every: int = 1
    spike_factor: float = 0.0
    ema_alpha: float = 0.1
    warmup_checks: int = 5


class LossTracker:
    """Host-side EMA + finiteness checks over supervised loss fetches.
    One instance per train() call (the EMA must restart with the run —
    a rolled-back run re-learns its baseline from the replayed steps)."""

    def __init__(self, cfg: SupervisionConfig):
        self.cfg = cfg
        self.ema: Optional[float] = None
        self.checks = 0

    def check(self, step: int, loss: float) -> None:
        if not math.isfinite(loss):
            raise NonFiniteLossError(step, loss)
        self.checks += 1
        if self.ema is None:
            self.ema = loss
            return
        if (self.cfg.spike_factor > 0
                and self.checks > self.cfg.warmup_checks
                and loss > self.cfg.spike_factor * self.ema):
            raise LossSpikeError(step, loss, self.ema,
                                 self.cfg.spike_factor)
        a = self.cfg.ema_alpha
        self.ema = (1 - a) * self.ema + a * loss


@dataclass
class SupervisedResult:
    """``train()``'s result plus the recovery story that produced it."""

    result: Any                            # runner.TrainResult
    metrics: Metrics
    attempts: int = 1

    @property
    def counters(self) -> Dict[str, float]:
        return dict(self.metrics.counters)


def supervised_train(cfg, *, checkpoint_manager, mesh=None,
                     logger: Optional[StepLogger] = None,
                     supervision: SupervisionConfig = SupervisionConfig(),
                     max_rollbacks: int = 3, skip_window: int = 1,
                     metrics: Optional[Metrics] = None,
                     resume: bool = False, telemetry=None,
                     **train_kwargs) -> SupervisedResult:
    """Run ``train()`` under loss supervision with automatic rollback.

    ``skip_window`` — optimizer steps the data cursor advances past the
    offending window when the SAME step fails twice (a transient fault
    gets one clean replay first; only a repeat implicates the data).
    ``max_rollbacks`` bounds total recoveries before
    :class:`SupervisionExhausted`. ``telemetry`` (utils.telemetry)
    marks every rollback / data skip / exhaustion as an instant on the
    same timeline the runner's dispatch spans land on, and is passed
    through to ``train()``. Extra ``train_kwargs`` pass through
    to :func:`~replicatinggpt_tpu.train.runner.train`.
    """
    from ..train.runner import train      # lazy: runner imports faults

    logger = logger or StepLogger()
    metrics = metrics or Metrics()
    tel = telemetry or NULL
    failures_at: Dict[int, int] = {}
    skip = 0
    for attempt in range(max_rollbacks + 1):
        try:
            res = train(cfg, mesh=mesh, logger=logger,
                        checkpoint_manager=checkpoint_manager,
                        resume=resume, supervision=supervision,
                        skip_data_steps=skip, telemetry=tel,
                        **train_kwargs)
            for k, v in checkpoint_manager.recovery.items():
                if v:
                    metrics.inc(k, v)
            return SupervisedResult(result=res, metrics=metrics,
                                    attempts=attempt + 1)
        except (NonFiniteLossError, LossSpikeError) as e:
            metrics.inc("rollbacks")
            step = getattr(e, "step", -1)
            failures_at[step] = failures_at.get(step, 0) + 1
            tel.instant("rollback", step=step, attempt=attempt + 1,
                        error=type(e).__name__)
            logger.log(f"supervisor: {e} — rollback "
                       f"{attempt + 1}/{max_rollbacks} to last good "
                       f"checkpoint")
            if attempt == max_rollbacks:
                for k, v in checkpoint_manager.recovery.items():
                    if v:
                        metrics.inc(k, v)
                tel.instant("supervision_exhausted", step=step,
                            rollbacks=max_rollbacks + 1)
                raise SupervisionExhausted(
                    f"training failed {max_rollbacks + 1} times "
                    f"(last: {e}); no recovery path left") from e
            # a durable rollback target: block until the last good save
            # is actually on disk before re-entering
            checkpoint_manager.wait()
            resume = True
            if failures_at[step] > 1 and skip_window > 0:
                # same step failed after a clean replay: the data window
                # itself is implicated. The skip is applied at the
                # RESTORED checkpoint's cursor, so it must cover the
                # whole distance from there THROUGH the offending
                # window — skipping only skip_window batches at the
                # restored cursor would shift the stream and feed the
                # poisoned window to an earlier step instead
                restored = checkpoint_manager.latest_step() or 0
                skip = max(step - restored, 0) + skip_window
                metrics.inc("data_skips")
                tel.instant("data_skip", step=step, skip=skip)
                logger.log(f"supervisor: step {step} failed again after "
                           f"rollback; advancing data cursor {skip} "
                           f"step(s) (from checkpoint {restored} past "
                           f"the offending window)")
            else:
                skip = 0
    raise AssertionError("unreachable")
