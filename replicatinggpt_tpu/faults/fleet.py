"""Fleet-level fault kinds: deterministic chaos for the serving fleet.

PR 4's faults are process-local (a slow step, a corrupt checkpoint, a
collapsing drafter). The fleet tier (serve/router.py over N engine
replicas) adds the failure modes a single process cannot have: a whole
replica dying with requests in flight, a replica wedging/partitioning
(alive but not making progress), and traffic skew that concentrates
load on one cached prefix. Same :class:`~.inject.FaultPlan` machinery —
explicit step/session indices, one-shot counting, no-op by default —
so a fleet chaos soak is exactly as replayable as a process-level one.

Sites (consulted once per router step / per generated session):

- ``fleet/step`` with kind ``replica_kill``: the router abandons
  replica ``int(arg)`` at router step ``at`` — stops stepping it,
  closes its journal, and requeues its accepted-but-unfinished
  requests from that journal onto surviving replicas (the crash-journal
  path, now cross-replica).
- ``fleet/step`` with kind ``replica_wedge``: replica ``int(arg2)``'s
  next ``times`` steps each stall ``arg`` seconds (injected INSIDE the
  router's per-replica step timing, so the health probe sees exactly
  what a wedged device or a network partition to that replica looks
  like: the replica stops completing steps on budget).
- ``fleet/session`` with kind ``hot_key_skew``: the load generator
  collapses each eligible session onto prefix group 0 with probability
  ``arg`` (seeded — deterministic per loadgen seed), turning a uniform
  session mix into hot-key traffic that hammers one radix subtree and
  one affinity target.
- ``fleet/step`` with kind ``proc_kill``: the multi-process fleet's
  real death — the supervisor (faults/procsup.py) SIGKILLs worker
  ``int(arg)``'s actual OS process at router step ``at``. No Python
  cleanup runs in the worker; recovery is supervised restart + the
  worker's own journal replay (or, past the restart budget,
  router-side requeue onto survivors). In-process routers (no
  supervisor attached) log and ignore it.
- ``fleet/step`` with kind ``proc_hang``: SIGSTOP worker
  ``int(arg2)``'s process for ``int(arg)`` supervisor ticks, then
  SIGCONT. From the router's side this is indistinguishable from a
  wedged device: RPC calls time out while the process stays "alive" —
  exactly what the wedge probe and hedged re-route must handle.
- ``fleet/step`` with kind ``host_loss``: the WHOLE HOST vanishes —
  SIGKILL worker ``int(arg)``'s process AND delete its working
  directory, crash journal included (the spot-VM / TPU-maintenance
  preemption scenario). Unlike ``proc_kill``, the restarted worker
  replays NOTHING: recovery is the router's own request ledger —
  every accepted-but-unfinished request requeues from the router side
  and the delivery ledger keeps the streams exactly-once. The fault
  nothing on the worker's filesystem can survive, by construction.
"""

from __future__ import annotations

from typing import Optional

from .inject import Fault, fire

#: router step seam — fired once per Router.step with the router's step
#: counter as the index
FLEET_STEP = "fleet/step"
#: loadgen session-creation seam — fired once per session with the
#: session index
FLEET_SESSION = "fleet/session"
#: disaggregated page-transfer seam (serve/disagg.py) — fired once per
#: chunk round-trip inside a running transfer, with the chunk index
FLEET_TRANSFER = "fleet/transfer"

KIND_REPLICA_KILL = "replica_kill"
KIND_REPLICA_WEDGE = "replica_wedge"
KIND_HOT_KEY_SKEW = "hot_key_skew"
#: mid-transfer host loss: at transfer chunk ``at``, replica
#: ``int(arg)`` — either tier — dies and the transfer aborts the way
#: a vanished host would (the router falls back to a full decode-tier
#: prefill; streams stay exactly-once through the delivery ledger)
KIND_TRANSFER_KILL = "transfer_kill"
#: process-level chaos (multi-process fleet only; needs a supervisor)
KIND_PROC_KILL = "proc_kill"
KIND_PROC_HANG = "proc_hang"
#: host-level chaos: SIGKILL + journal/workdir deletion — the worker's
#: machine is gone, not just its process
KIND_HOST_LOSS = "host_loss"


def fleet_step_fault(step: int) -> Optional[Fault]:
    """The router's per-step seam: at most one fleet fault per step
    (None almost always — the no-plan fast path is one global read)."""
    return fire(FLEET_STEP, index=step)


def transfer_fault(chunk_index: int) -> Optional[Fault]:
    """The page-transfer per-chunk seam: at most one fault per chunk
    round-trip (None almost always — one global read)."""
    return fire(FLEET_TRANSFER, index=chunk_index)


def session_skew(session_index: int) -> float:
    """The loadgen's per-session seam: the hot-key collapse probability
    for this session (0.0 = no skew fault active)."""
    f = fire(FLEET_SESSION, index=session_index)
    if f is not None and f.kind == KIND_HOT_KEY_SKEW:
        return float(f.arg)
    return 0.0
