"""Fault injection + the supervision layer that survives it (PR 4).

- ``inject``: deterministic :class:`FaultPlan` + the no-op-by-default
  seams compiled into train/checkpoint/serve hot paths;
- ``supervise``: train self-healing — loss-spike/NaN rollback to the
  last *verified* checkpoint, data-cursor advance, bounded retries;
- ``watchdog``: serve self-healing policies — step-stall watchdog,
  speculative auto-disable with re-probe, load shedding.

The ops story (fault matrix -> detection -> automatic recovery ->
operator action) lives in docs/robustness.md.
"""

from .inject import Fault, FaultPlan, active, clear, fire, install, installed
from .supervise import (LossSpikeError, NonFiniteLossError,
                        SupervisedResult, SupervisionConfig,
                        SupervisionExhausted, supervised_train)
from .watchdog import (DEFAULT_SERVE_RESILIENCE, LoadShedder,
                       ResilienceConfig, SpecHealth, StepWatchdog)

__all__ = [
    "Fault", "FaultPlan", "active", "clear", "fire", "install", "installed",
    "LossSpikeError", "NonFiniteLossError", "SupervisedResult",
    "SupervisionConfig", "SupervisionExhausted", "supervised_train",
    "DEFAULT_SERVE_RESILIENCE", "LoadShedder", "ResilienceConfig",
    "SpecHealth", "StepWatchdog",
]
