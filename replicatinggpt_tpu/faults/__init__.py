"""Fault injection + the supervision layer that survives it (PR 4).

- ``inject``: deterministic :class:`FaultPlan` + the no-op-by-default
  seams compiled into train/checkpoint/serve hot paths;
- ``supervise``: train self-healing — loss-spike/NaN rollback to the
  last *verified* checkpoint, data-cursor advance, bounded retries;
- ``watchdog``: serve self-healing policies — step-stall watchdog,
  speculative auto-disable with re-probe, load shedding;
- ``fleet``: fleet-level fault kinds (replica kill / wedge-partition /
  hot-key skew) behind the same plan machinery, consulted by
  serve/router.py and serve/loadgen.py;
- ``netchaos``: message-level network faults (drop / duplicate /
  reorder / delay / trickle / corrupt-frame / partition) injected by
  :class:`FaultyTransport` around the serve/rpc.py client — the layer
  the idempotent-RPC hardening is proven against.

The ops story (fault matrix -> detection -> automatic recovery ->
operator action) lives in docs/robustness.md.
"""

from .fleet import (FLEET_SESSION, FLEET_STEP, KIND_HOT_KEY_SKEW,
                    KIND_REPLICA_KILL, KIND_REPLICA_WEDGE,
                    fleet_step_fault, session_skew)
from .inject import Fault, FaultPlan, active, clear, fire, install, installed
from .netchaos import (KIND_NET_CORRUPT, KIND_NET_DELAY, KIND_NET_DROP,
                       KIND_NET_DUP, KIND_NET_PARTITION,
                       KIND_NET_REORDER, KIND_NET_TRICKLE, NET_CALL,
                       NET_KINDS, FaultyTransport, net_call_fault,
                       net_site)
from .supervise import (LossSpikeError, NonFiniteLossError,
                        SupervisedResult, SupervisionConfig,
                        SupervisionExhausted, supervised_train)
from .watchdog import (DEFAULT_SERVE_RESILIENCE, LoadShedder,
                       ResilienceConfig, SpecHealth, StepWatchdog)

__all__ = [
    "Fault", "FaultPlan", "active", "clear", "fire", "install", "installed",
    "LossSpikeError", "NonFiniteLossError", "SupervisedResult",
    "SupervisionConfig", "SupervisionExhausted", "supervised_train",
    "DEFAULT_SERVE_RESILIENCE", "LoadShedder", "ResilienceConfig",
    "SpecHealth", "StepWatchdog",
    "FLEET_SESSION", "FLEET_STEP", "KIND_HOT_KEY_SKEW",
    "KIND_REPLICA_KILL", "KIND_REPLICA_WEDGE", "fleet_step_fault",
    "session_skew",
    "FaultyTransport", "KIND_NET_CORRUPT", "KIND_NET_DELAY",
    "KIND_NET_DROP", "KIND_NET_DUP", "KIND_NET_PARTITION",
    "KIND_NET_REORDER", "KIND_NET_TRICKLE", "NET_CALL", "NET_KINDS",
    "net_call_fault", "net_site",
]
