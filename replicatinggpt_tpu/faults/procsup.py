"""Process supervisor: RPC registration, restart-on-exit, backoff,
quarantine, rolling restarts, autoscaling — and real process/host-level
chaos for the multi-process fleet.

PR 4's supervision heals *inside* a process (rollback, watchdog,
shedding); PR 8's router heals *across* in-process replicas. This
module owns the replicas that are worker **processes**
(serve/worker.py): something must notice when one of them actually
dies, and something must decide how many of them there should BE. The
supervisor owns both policies; the router (serve/router.py) owns the
request ledger. The split is deliberate — the router decides what
happens to *requests* (keep waiting for a restart, requeue onto
survivors), the supervisor decides what happens to *processes*
(restart with backoff, give up and quarantine, spawn more under load,
drain the idle):

- **Registration over RPC**: the supervisor runs a poll-driven
  :class:`~..serve.rpc.RpcListener`; every spawned worker gets
  ``--router-addr`` and, once warmed + journal-replayed + bound, sends
  ONE ``register`` frame ``{port, pid, gen, worker_idx, replayed,
  proto, shape_hash}``. The handshake crosses the network, not a
  shared filesystem — no ready files — so a worker is placeable on
  any host that can reach the listener (an *unmanaged* worker
  registering with ``worker_idx=-1`` joins the fleet as a brand-new
  replica: start ``serve-worker --router-addr host:port`` anywhere).
  The handshake carries :data:`~..serve.rpc.PROTO_VERSION` and
  :func:`~..serve.rpc.engine_shape_hash`; a mismatched worker build is
  rejected with a typed :class:`~..serve.rpc.RpcProtocolError` at
  registration — exit code 3, never a codec drift mid-traffic. The
  fleet's expected shape is pinned by config
  (``SupervisorConfig.expect_shape_hash``) or by the first successful
  registration.
- **Death detection**: ``Popen.poll`` per tick, plus periodic RPC
  ``health`` probes with short timeouts (a zombie that holds its port
  but answers nothing is as dead as an exited one — two consecutive
  probe failures escalate to SIGKILL so the exit path takes over).
- **Restart-on-exit**: an unexpected exit marks the replica down in
  the router (its in-flight ledger entries WAIT — the restarted worker
  replays its journal and resumes them), then respawns after an
  exponential backoff (``backoff_s * backoff_mult^n``). Each spawn
  carries a fresh generation; the supervisor attaches the router only
  on the registration message showing the generation it launched.
- **Restart budget → quarantine**: past ``restart_budget`` *crash*
  restarts (intentional rolling-restart stops are free), the
  supervisor stops trying: ``Router.abandon_replica`` requeues the
  worker's in-flight work onto the survivors (from the router's OWN
  ledger — the dead worker's disk is never read) and the replica
  leaves rotation for good.
- **Rolling restart**: replica by replica — drain (the router
  migrates its in-flight requests onto the rest of the fleet), stop
  gracefully (``shutdown`` RPC, SIGTERM fallback), respawn, wait
  registered+attached, move on. At least ``n-1`` workers serve at
  every moment, so a fleet of two or more drops nothing; ``/readyz``
  reports 503 exactly when zero routable warmed workers remain.
- **Autoscaling** (:class:`AutoscaleConfig` + a ``spec_factory``):
  the supervisor reads the offered-load/occupancy gauges the router
  already exports (``Router.offered_load``) every tick. Sustained
  backlog (queued work above ``up_backlog_per_worker`` per routable
  worker for ``up_patience`` ticks) spawns a fresh worker — it warms,
  registers, attaches, takes traffic, zero recompiles for anyone else.
  A sustained lull (empty queues, occupancy the smaller fleet can
  hold, ``down_patience`` ticks) retires the highest-index worker
  through the SAME drain→shutdown path a rolling restart uses — its
  in-flight work migrates, it exits, and it is NOT respawned
  (``RETIRED``). Scale actions are ``cooldown_ticks`` apart, bounded
  by ``[min_workers, max_workers]``. A rolling restart is therefore
  just the degenerate deploy: drain→respawn instead of drain→retire.
- **Chaos**: ``proc_kill`` (a real ``SIGKILL``), ``proc_hang``
  (``SIGSTOP`` for N ticks), and ``host_loss`` — SIGKILL **plus
  deletion of the worker's whole working directory, crash journal
  included**: the spot-VM/TPU-preemption scenario where the machine is
  gone, not just the process. The respawned worker replays nothing;
  recovery is the router's own request ledger. All three arrive
  through the standard ``FaultPlan`` machinery (``fleet/step`` —
  faults/fleet.py).

Everything is ticked from the same single-threaded loop that steps the
router (the HTTP driver task, or the fleet replay loop): one
``supervisor.tick()`` after each ``router.step()``. No threads, no
signals-as-control-flow — deaths are observed, never raced; the
registration listener is polled, never awaited.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import sys
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, List, Optional

# NOTE: serve.* imports stay function-local in this module — importing
# the serve package pulls jax, and the supervisor must stay importable
# from jax-free contexts (unit tests over stub routers included)

#: handle lifecycle states
RUNNING = "running"
BACKOFF = "backoff"
SPAWNING = "spawning"       # process launched, registration not seen yet
QUARANTINED = "quarantined"
STOPPED = "stopped"
RETIRED = "retired"         # scale-down complete: exited, not respawned


@dataclass(frozen=True)
class SupervisorConfig:
    """Restart policy knobs (docs/robustness.md has the fault matrix)."""

    #: crash restarts per worker before quarantine (intentional
    #: rolling-restart stops do not count)
    restart_budget: int = 3
    backoff_s: float = 0.5
    backoff_mult: float = 2.0
    #: a spawned worker must REGISTER within this budget (covers jax
    #: import + compile warmup) or the spawn counts as a crash
    ready_timeout_s: float = 180.0
    #: RPC health-probe budget; two consecutive failures escalate to
    #: SIGKILL
    probe_timeout_s: float = 2.0
    #: probe every N ticks (0 disables probing — the router's own step
    #: RPC failures still catch deaths)
    probe_every: int = 8
    #: required engine_shape_hash for registering workers; None = the
    #: first successful registration pins the fleet's shape, and every
    #: later worker must match it (RpcProtocolError otherwise)
    expect_shape_hash: Optional[str] = None


@dataclass(frozen=True)
class AutoscaleConfig:
    """Elastic fleet sizing from the router's own gauges. The
    supervisor reads ``Router.offered_load()`` once per tick; patience
    and cooldown are in ticks (one tick per router step), so decisions
    are as deterministic as the replay driving them."""

    min_workers: int = 1
    max_workers: int = 4
    #: queued work per routable worker that counts as sustained
    #: backlog (scale-up pressure)
    up_backlog_per_worker: float = 2.0
    up_patience: int = 4
    #: scale down only when queues are empty AND the active slots
    #: would fit the remaining workers at this per-worker occupancy
    down_active_per_worker: float = 1.0
    down_patience: int = 32
    #: minimum ticks between scale actions (a fresh worker must get a
    #: chance to absorb load before the next decision)
    cooldown_ticks: int = 32


@dataclass
class WorkerSpec:
    """How to (re)launch one worker. ``cmd`` is the full command minus
    the per-spawn ``--gen``/``--worker-idx``/``--router-addr``; the
    supervisor appends those. ``workdir`` is the worker's PRIVATE
    directory (journal + log) — nothing else ever reads it; host_loss
    chaos deletes it wholesale."""

    idx: int
    cmd: List[str]
    journal_path: str
    workdir: Optional[str] = None
    log_path: Optional[str] = None
    env: Optional[dict] = None


@dataclass
class WorkerHandle:
    spec: WorkerSpec
    proc: Optional[subprocess.Popen] = None
    state: str = STOPPED
    gen: int = -1
    pid: Optional[int] = None
    restarts: int = 0          # every respawn (rolling included)
    crash_restarts: int = 0    # budget-counted respawns
    backoff_until: float = 0.0
    spawn_t: float = 0.0
    hang_ticks: int = 0        # SIGSTOP chaos: SIGCONT when it hits 0
    probe_failures: int = 0
    intentional_stop: bool = False
    retiring: bool = False     # scale-down in progress: exit → RETIRED
    events: List[str] = field(default_factory=list)


class ProcSupervisor:
    """Owns the worker processes of one fleet. Drive it with
    :meth:`tick` from the router's loop; it talks back to the router
    through ``mark_down`` / ``attach_replica`` / ``abandon_replica`` /
    ``add_replica`` / ``offered_load``.
    """

    def __init__(self, specs: List[WorkerSpec],
                 cfg: SupervisorConfig = SupervisorConfig(),
                 autoscale: Optional[AutoscaleConfig] = None,
                 spec_factory: Optional[
                     Callable[[int], WorkerSpec]] = None,
                 listen_host: str = "127.0.0.1"):
        self.cfg = cfg
        self.autoscale = autoscale
        self.spec_factory = spec_factory
        self.handles = [WorkerHandle(spec=s) for s in specs]
        self.router = None          # attach_router
        self.ticks = 0
        self._rolling: List[int] = []
        self._rolling_phase = ""
        self._rolling_target_gen = -1
        self.events: List[str] = []
        #: the registration endpoint every worker handshakes with
        #: (--router-addr); polled from tick()/start_all(), never blocks
        from ..serve.rpc import RpcListener
        self.listener = RpcListener(host=listen_host)
        self.expect_shape_hash = cfg.expect_shape_hash
        #: replica indices of unmanaged workers that registered from
        #: outside (no handle, no restart policy — their host owns that)
        self.external: List[int] = []
        self.scale_ups = 0
        self.scale_downs = 0
        #: most workers ever provisioned CONCURRENTLY (scale-downs
        #: between scale-ups don't inflate it — the honest elasticity
        #: peak for the bench artifact)
        self.peak_workers = len(specs)
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale_tick = 0
        #: registration reply cache, keyed by the worker's idem key: a
        #: duplicated or blind-retried register frame is answered with
        #: the ORIGINAL attach response instead of reconciling the
        #: ledger twice (serve/rpc.py idempotency contract, GL024)
        self._reg_replies: "OrderedDict[str, dict]" = OrderedDict()

    def attach_router(self, router) -> None:
        self.router = router
        router.supervisor = self

    @property
    def router_addr(self) -> str:
        """host:port workers register with (the --router-addr value)."""
        return self.listener.addr

    @property
    def reviving(self) -> bool:
        """True while any worker is on its way back (spawning, backing
        off, or intentionally stopped for a rolling restart) — the
        router's requeue ladder holds its retry budget while this is
        set instead of burning attempts against a fleet that is mid-
        recovery (a zero-routable window during a single-worker rolling
        restart must not reject the held requests). A RETIRING worker
        is leaving on purpose and does not count."""
        return any(h.state in (SPAWNING, BACKOFF)
                   or (h.intentional_stop and not h.retiring)
                   for h in self.handles)

    def _handle(self, idx: int) -> Optional[WorkerHandle]:
        """Handle by WORKER INDEX (== router replica index). Position
        in ``handles`` no longer equals the index once external
        replicas joined the router between scale-ups."""
        for h in self.handles:
            if h.spec.idx == idx:
                return h
        return None

    # ------------------------------------------------------------- spawn

    def _event(self, msg: str) -> None:
        self.events.append(msg)
        if len(self.events) > 256:
            del self.events[:len(self.events) - 256]
        if self.router is not None:
            from ..utils.telemetry import ROUTER_TRACK
            self.router._event(f"supervisor: {msg}")
            self.router.tel.instant("supervisor", ROUTER_TRACK,
                                    note=msg)

    def _spawn(self, h: WorkerHandle) -> None:
        h.gen += 1
        h.restarts += int(h.gen > 0)
        if h.spec.workdir:
            # host_loss chaos deletes the whole workdir; a respawn is
            # the replacement host coming up with an empty disk
            os.makedirs(h.spec.workdir, exist_ok=True)
        stdout = subprocess.DEVNULL
        if h.spec.log_path:
            os.makedirs(os.path.dirname(h.spec.log_path) or ".",
                        exist_ok=True)
            stdout = open(h.spec.log_path, "a")
        env = {**os.environ, **(h.spec.env or {})}
        h.proc = subprocess.Popen(
            h.spec.cmd + ["--gen", str(h.gen),
                          "--worker-idx", str(h.spec.idx),
                          "--router-addr", self.router_addr],
            stdout=stdout, stderr=stdout, env=env)
        if stdout is not subprocess.DEVNULL:
            stdout.close()      # Popen holds its own dup
        h.pid = h.proc.pid
        h.state = SPAWNING
        h.spawn_t = time.monotonic()
        h.probe_failures = 0
        self._event(f"worker {h.spec.idx} spawned "
                    f"(pid {h.pid}, gen {h.gen})")

    # ------------------------------------------------------ registration

    def _handle_register(self, doc: dict, peer_host: str) -> dict:
        """The RpcListener handler: validate the handshake, attach the
        router. Raising :class:`RpcProtocolError` answers the worker
        with ``kind="protocol"`` — its client raises the typed error
        and the worker exits 3 instead of retrying.

        Registration MUTATES the router (attach reconciliation), so it
        carries an idempotency key like the other mutating verbs: a
        worker that registered but lost the response blind-retries the
        same frame, and the reply cache answers it with the original
        attach result instead of reconciling twice. Rejections are NOT
        cached — a retried bad handshake must re-validate."""
        idem = doc.get("idem")
        if idem is not None and idem in self._reg_replies:
            return {**self._reg_replies[idem], "idem_hit": True}
        resp = self._register_attach(doc, peer_host)
        if idem is not None:
            self._reg_replies[idem] = resp
            while len(self._reg_replies) > 64:
                self._reg_replies.popitem(last=False)
        return resp

    def _register_attach(self, doc: dict, peer_host: str) -> dict:
        from ..serve.rpc import PROTO_VERSION, RpcProtocolError
        router = self.router
        assert router is not None, "attach_router first"
        proto = int(doc.get("proto", -1))
        if proto != PROTO_VERSION:
            raise RpcProtocolError(
                f"worker speaks protocol v{proto}, router v"
                f"{PROTO_VERSION} — rebuild the worker")
        shape = str(doc.get("shape_hash", ""))
        if self.expect_shape_hash is None:
            # first successful registration pins the fleet's shape
            self.expect_shape_hash = shape
        elif shape != self.expect_shape_hash:
            raise RpcProtocolError(
                f"worker engine shape {shape} != fleet "
                f"{self.expect_shape_hash} — a different model or "
                f"engine build cannot join this fleet")
        idx = int(doc.get("worker_idx", -1))
        gen = int(doc.get("gen", 0))
        port = int(doc["port"])
        pid = int(doc.get("pid", 0))
        # disaggregation role + page geometry (serve/disagg.py): the
        # worker advertises both; older workers default to the
        # colocated "mixed" role
        tier = str(doc.get("tier", "mixed"))
        page_size = int(doc.get("page_size", 0))
        h = self._handle(idx) if idx >= 0 else None
        if h is not None:
            if gen != h.gen:
                # a stale incarnation (pre-restart straggler) — its
                # replacement is the one the supervisor launched
                raise ValueError(
                    f"stale generation {gen} (current {h.gen})")
            info = router.attach_replica(idx, port, pid=pid, gen=gen,
                                         host=peer_host, tier=tier,
                                         page_size=page_size)
            router.replicas[idx].restarts = h.restarts
            h.state = RUNNING
            h.pid = pid
            h.probe_failures = 0
            self._event(f"worker {idx} registered+attached "
                        f"(gen {gen}, host {peer_host}, "
                        f"kept {info['kept']}, "
                        f"requeued {info['requeued']}, "
                        f"ghosts {info['ghosts']})")
            return {"idx": idx, **info}
        # an UNMANAGED worker joining from anywhere: grow the fleet.
        # No handle — its lifecycle belongs to whoever spawned it; the
        # router's step-RPC failures still mark it down if it vanishes.
        from ..serve.router import RemoteReplica
        new_idx = len(router.replicas)
        rep = RemoteReplica(
            new_idx, None, host=peer_host,
            rpc_timeout_s=router.rcfg.step_timeout_s,
            step_timeout_s=router.rcfg.step_timeout_s)
        router.add_replica(rep)
        info = router.attach_replica(new_idx, port, pid=pid, gen=gen,
                                     host=peer_host, tier=tier,
                                     page_size=page_size)
        self.external.append(new_idx)
        self._event(f"external worker joined as replica {new_idx} "
                    f"(host {peer_host}, pid {pid})")
        return {"idx": new_idx, **info}

    def _poll_registrations(self) -> int:
        return self.listener.poll(self._handle_register)

    def start_all(self, wait: bool = True,
                  timeout_s: Optional[float] = None) -> None:
        """Spawn every worker; with ``wait`` (the default), block until
        each one registered and attached to the router. A failed (or
        interrupted) startup stops EVERY spawned worker before raising
        — an orphaned worker would hold its journal flock and crash-
        loop the next run's replacement with JournalBusyError."""
        for h in self.handles:
            self._spawn(h)
        if not wait:
            return
        budget = timeout_s or self.cfg.ready_timeout_s
        deadline = time.monotonic() + budget
        try:
            while time.monotonic() < deadline:
                self._poll_registrations()
                for h in self.handles:
                    if h.state == SPAWNING:
                        self._check_spawn(h)
                    elif (h.state == BACKOFF
                          and time.monotonic() >= h.backoff_until):
                        # a worker that crashed during startup retries
                        # inside the wait (the tick loop is not running
                        # yet) — without this, one startup crash burns
                        # the whole ready budget
                        self._spawn(h)
                if all(h.state == RUNNING for h in self.handles):
                    return
                if any(h.state == QUARANTINED for h in self.handles):
                    break          # crash-looped out of the budget:
                    #                fail fast, don't burn the deadline
                time.sleep(0.05)
        except BaseException:      # Ctrl-C mid-warmup included
            self.stop_all()
            raise
        bad = [h.spec.idx for h in self.handles if h.state != RUNNING]
        logs = [self._handle(i).spec.log_path for i in bad]
        self.stop_all()
        raise RuntimeError(
            f"workers {bad} not ready within {budget}s (see {logs})")

    def stop_all(self, timeout_s: float = 15.0) -> None:
        for h in self.handles:
            h.intentional_stop = True
            h.retiring = False
            h.state = STOPPED
            if h.proc is not None and h.proc.poll() is None:
                if h.hang_ticks:          # a stopped process cannot
                    self._signal(h, signal.SIGCONT)   # handle SIGTERM
                    h.hang_ticks = 0
                self._signal(h, signal.SIGTERM)
        deadline = time.monotonic() + timeout_s
        for h in self.handles:
            if h.proc is None:
                continue
            while (h.proc.poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            if h.proc.poll() is None:
                self._signal(h, signal.SIGKILL)
                h.proc.wait()
        self.listener.close()

    @staticmethod
    def _signal(h: WorkerHandle, sig) -> None:
        try:
            os.kill(h.proc.pid, sig)
        except (OSError, AttributeError):
            pass

    # -------------------------------------------------------------- tick

    def tick(self) -> None:
        """One supervision pass: serve pending registrations, resume
        chaos hangs, observe deaths, advance backoffs/spawns, probe
        health, advance any rolling restart, make the autoscale
        decision. Call after every ``router.step()`` (and on idle loop
        iterations — restarts must progress while the fleet waits)."""
        router = self.router
        assert router is not None, "attach_router first"
        self.ticks += 1
        self._poll_registrations()
        for h in self.handles:
            if h.hang_ticks > 0:
                h.hang_ticks -= 1
                if h.hang_ticks == 0:
                    self._signal(h, signal.SIGCONT)
                    self._event(f"worker {h.spec.idx} SIGCONT "
                                f"(hang over)")
            if h.state == RUNNING:
                if h.proc is not None and h.proc.poll() is not None:
                    self._on_exit(h, h.proc.returncode)
                    continue
                self._maybe_probe(h)
                # the router declared it down (RPC refused / worker
                # dispatch broken) but the process lingers: a zombie —
                # SIGKILL it so the exit path owns recovery
                if (not router.replicas[h.spec.idx].alive
                        and h.hang_ticks == 0):
                    self._event(f"worker {h.spec.idx} unreachable but "
                                f"process alive — escalating SIGKILL")
                    self._signal(h, signal.SIGKILL)
            elif h.state == BACKOFF:
                if time.monotonic() >= h.backoff_until:
                    self._spawn(h)
            elif h.state == SPAWNING:
                self._check_spawn(h)
        self._tick_rolling()
        self._tick_autoscale()

    def _on_exit(self, h: WorkerHandle, rc) -> None:
        router = self.router
        router.mark_down(h.spec.idx,
                         f"process exited rc={rc}")
        if h.intentional_stop:
            h.intentional_stop = False
            if h.retiring:
                # scale-down complete: drained, stopped, NOT respawned
                h.retiring = False
                h.state = RETIRED
                self._event(f"worker {h.spec.idx} retired "
                            f"(scale-down complete)")
                return
            # rolling restart / operator stop: free respawn, no budget
            self._event(f"worker {h.spec.idx} stopped (intentional); "
                        f"respawning")
            self._spawn(h)
            return
        h.crash_restarts += 1
        if h.crash_restarts > self.cfg.restart_budget:
            h.state = QUARANTINED
            self._event(f"worker {h.spec.idx} exceeded restart budget "
                        f"({self.cfg.restart_budget}); quarantined — "
                        f"requeueing its in-flight work onto survivors")
            router.abandon_replica(h.spec.idx)
            return
        delay = (self.cfg.backoff_s
                 * self.cfg.backoff_mult ** (h.crash_restarts - 1))
        h.state = BACKOFF
        h.backoff_until = time.monotonic() + delay
        self._event(f"worker {h.spec.idx} died rc={rc}; restart "
                    f"{h.crash_restarts}/{self.cfg.restart_budget} in "
                    f"{delay:.2f}s")

    def _check_spawn(self, h: WorkerHandle) -> None:
        """A SPAWNING worker either registers (the listener handler
        flips it RUNNING), dies (fold into the crash path), or blows
        the ready budget (SIGKILL so the exit path takes over)."""
        if h.proc is not None and h.proc.poll() is not None:
            # died during startup — counts as a crash
            h.state = RUNNING   # route through the common exit path
            self._on_exit(h, h.proc.returncode)
            return
        if (time.monotonic() - h.spawn_t
                > self.cfg.ready_timeout_s):
            self._event(f"worker {h.spec.idx} missed ready deadline; "
                        f"killing")
            self._signal(h, signal.SIGKILL)

    def _maybe_probe(self, h: WorkerHandle) -> None:
        if (self.cfg.probe_every <= 0
                or self.ticks % self.cfg.probe_every
                or h.hang_ticks > 0):   # a chaos-hung worker is
            return                      # *supposed* to be unresponsive
        rep = self.router.replicas[h.spec.idx]
        try:
            rep.client.call("health",
                            timeout_s=self.cfg.probe_timeout_s)
            h.probe_failures = 0
        except Exception:  # noqa: BLE001 — timeout, refusal, garbage:
            # the probe only counts failures, the escalation decides
            h.probe_failures += 1
            if h.probe_failures >= 2:
                self._event(f"worker {h.spec.idx} failed "
                            f"{h.probe_failures} health probes; "
                            f"escalating SIGKILL")
                self._signal(h, signal.SIGKILL)

    # --------------------------------------------------------- autoscale

    def _tick_autoscale(self) -> None:
        """The elasticity decision, one per tick: read the router's
        offered-load gauges, track sustained pressure either way, act
        at patience through the SAME spawn/drain paths restarts use."""
        a = self.autoscale
        if a is None or self.spec_factory is None or self._rolling:
            return
        provisioned = [h for h in self.handles
                       if not h.retiring
                       and h.state in (RUNNING, SPAWNING, BACKOFF)]
        if any(h.state == SPAWNING for h in provisioned):
            return              # let the in-flight scale-up land first
        load = self.router.offered_load()
        n_routable = load["n_routable"]
        if self.ticks - self._last_scale_tick < a.cooldown_ticks:
            return
        if (load["queued"]
                > a.up_backlog_per_worker * max(n_routable, 1)):
            self._down_streak = 0
            self._up_streak += 1
            if (self._up_streak >= a.up_patience
                    and len(provisioned) < a.max_workers):
                self.scale_up()
        elif (load["queued"] == 0
              and n_routable > 1
              and len(provisioned) > a.min_workers
              and load["active"] <= (a.down_active_per_worker
                                     * (n_routable - 1))):
            self._up_streak = 0
            self._down_streak += 1
            if self._down_streak >= a.down_patience:
                self.scale_down()
        else:
            self._up_streak = self._down_streak = 0

    def scale_up(self) -> int:
        """Grow the fleet by one worker: a fresh spec from the
        factory, a fresh router replica slot, a normal spawn — it
        warms itself, registers, attaches, takes traffic."""
        assert self.spec_factory is not None, "no spec_factory"
        from ..serve.router import RemoteReplica
        router = self.router
        idx = len(router.replicas)
        spec = self.spec_factory(idx)
        spec.idx = idx
        h = WorkerHandle(spec=spec)
        self.handles.append(h)
        router.add_replica(RemoteReplica(
            idx, None,
            rpc_timeout_s=router.rcfg.step_timeout_s,
            step_timeout_s=router.rcfg.step_timeout_s))
        self.scale_ups += 1
        self._last_scale_tick = self.ticks
        self._up_streak = self._down_streak = 0
        self.router.metrics.inc("fleet_scale_ups")
        self._event(f"autoscale: scale-UP — spawning worker {idx} "
                    f"(sustained backlog)")
        self._spawn(h)
        self.peak_workers = max(self.peak_workers, sum(
            1 for x in self.handles
            if not x.retiring and x.state in (RUNNING, SPAWNING,
                                              BACKOFF)))
        return idx

    def scale_down(self) -> Optional[int]:
        """Shrink the fleet by one worker through the rolling-restart
        drain path: the router migrates its in-flight work, the worker
        journals + exits, and the exit is terminal (RETIRED) instead
        of a respawn. Zero requests drop — that is the whole point of
        reusing the drain."""
        victims = [h for h in self.handles
                   if h.state == RUNNING and not h.retiring
                   and not h.intentional_stop]
        if not victims:
            return None
        h = victims[-1]            # highest index leaves first (LIFO)
        idx = h.spec.idx
        h.retiring = True
        h.intentional_stop = True
        self.scale_downs += 1
        self._last_scale_tick = self.ticks
        self._up_streak = self._down_streak = 0
        self.router.metrics.inc("fleet_scale_downs")
        self.router.drain_replica(idx)
        rep = self.router.replicas[idx]
        try:
            rep.client.call("drain", timeout_s=2.0)
            rep.client.call("shutdown", timeout_s=2.0)
        except Exception:  # noqa: BLE001 — graceful path failed;
            # SIGTERM says the same thing louder
            self._signal(h, signal.SIGTERM)
        self._event(f"autoscale: scale-DOWN — draining worker {idx} "
                    f"(sustained lull)")
        return idx

    # ------------------------------------------------------------- chaos

    def chaos_kill(self, idx: int) -> None:
        """``proc_kill``: a real SIGKILL — no cleanup, no flushes."""
        h = self._handle(idx)
        if h is None:
            return
        self._event(f"CHAOS proc_kill worker {idx} (pid {h.pid})")
        self._signal(h, signal.SIGKILL)

    def chaos_hang(self, idx: int, ticks: int) -> None:
        """``proc_hang``: SIGSTOP now, SIGCONT after ``ticks`` ticks."""
        h = self._handle(idx)
        if h is None:
            return
        self._event(f"CHAOS proc_hang worker {idx} for {ticks} ticks")
        h.hang_ticks = max(int(ticks), 1)
        self._signal(h, signal.SIGSTOP)

    def chaos_host_loss(self, idx: int) -> None:
        """``host_loss``: the worker's MACHINE is gone — SIGKILL the
        process and delete its working directory, crash journal
        included. The respawn is the replacement host coming up with
        an empty disk: it replays nothing, and the router's own ledger
        is the only recovery there is (which is the property under
        test)."""
        h = self._handle(idx)
        if h is None:
            return
        self._event(f"CHAOS host_loss worker {idx} (pid {h.pid}; "
                    f"journal + workdir deleted)")
        if h.hang_ticks:
            h.hang_ticks = 0       # a SIGSTOPped process still dies
        self._signal(h, signal.SIGKILL)
        if h.proc is not None:
            try:
                h.proc.wait(timeout=10)
            except (subprocess.TimeoutExpired, OSError):
                pass
        # the host took its disk with it: journal, logs, everything
        if h.spec.workdir:
            shutil.rmtree(h.spec.workdir, ignore_errors=True)
        else:
            try:
                os.remove(h.spec.journal_path)
            except OSError:
                pass

    # --------------------------------------------------- rolling restart

    @property
    def rolling_active(self) -> bool:
        return bool(self._rolling)

    def start_rolling_restart(self) -> None:
        """Queue a drain -> stop -> respawn -> reattach cycle over every
        worker, one at a time (ticked forward by :meth:`tick`)."""
        if self._rolling:
            return
        self._rolling = [h.spec.idx for h in self.handles
                         if h.state not in (QUARANTINED, RETIRED)]
        self._rolling_phase = "drain"
        self._event(f"rolling restart of workers {self._rolling}")

    def _tick_rolling(self) -> None:
        if not self._rolling:
            return
        router = self.router
        idx = self._rolling[0]
        h = self._handle(idx)
        if h is None:
            self._rolling.pop(0)
            return
        if self._rolling_phase == "drain":
            router.drain_replica(idx)
            h.intentional_stop = True
            #: advance only once THIS generation is gone and the NEXT
            #: one is attached — "running and alive" is already true in
            #: the instant after the shutdown RPC (the worker takes a
            #: moment to exit), and advancing on it would drain the
            #: whole fleet at once
            self._rolling_target_gen = h.gen + 1
            rep = router.replicas[idx]
            try:
                rep.client.call("drain", timeout_s=2.0)
                rep.client.call("shutdown", timeout_s=2.0)
            except Exception:  # noqa: BLE001 — graceful path failed;
                # SIGTERM says the same thing louder
                self._signal(h, signal.SIGTERM)
            self._rolling_phase = "await_restart"
        elif self._rolling_phase == "await_restart":
            if (h.gen >= self._rolling_target_gen
                    and h.state == RUNNING
                    and router.replicas[idx].alive):
                self._rolling.pop(0)
                self._rolling_phase = "drain"
                if not self._rolling:
                    self._event("rolling restart complete")
            elif h.state == QUARANTINED:
                # it crashed its way out of the budget mid-restart —
                # abandon the rolling pass for this worker
                self._rolling.pop(0)
                self._rolling_phase = "drain"


# -------------------------------------------------------------- builders

def _worker_env(env: Optional[dict]) -> dict:
    """The workers must import THIS package regardless of the caller's
    cwd (`python -m` resolves against the child's sys.path, and the
    repo is not necessarily pip-installed)."""
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(env or {})
    env.setdefault("PYTHONPATH", os.pathsep.join(
        p for p in (pkg_root, os.environ.get("PYTHONPATH")) if p))
    return env


def make_worker_spec(idx: int, workdir: str, config_args: List[str],
                     engine_args: Optional[List[str]] = None,
                     env: Optional[dict] = None,
                     tier: str = "mixed") -> WorkerSpec:
    """One ``serve-worker`` spec with a PRIVATE working directory
    (journal.jsonl + worker.log inside it). Nothing outside the worker
    process reads the directory — the router reconciles over RPC —
    and ``host_loss`` chaos deletes it wholesale. ``tier`` is the
    worker's disaggregation role (serve/disagg.py)."""
    os.makedirs(workdir, exist_ok=True)
    jpath = os.path.join(workdir, "journal.jsonl")
    log = os.path.join(workdir, "worker.log")
    cmd = [sys.executable, "-m", "replicatinggpt_tpu",
           "serve-worker", *config_args,
           "--port", "0", "--journal", jpath,
           *(["--tier", tier] if tier != "mixed" else []),
           *(engine_args or [])]
    return WorkerSpec(idx=idx, cmd=cmd, journal_path=jpath,
                      workdir=workdir, log_path=log,
                      env=_worker_env(env))


def make_worker_specs(n_workers: int, base_dir: str,
                      config_args: List[str],
                      engine_args: Optional[List[str]] = None,
                      env: Optional[dict] = None,
                      tiers: Optional[List[str]] = None
                      ) -> List[WorkerSpec]:
    """Specs for N ``serve-worker`` subprocesses, each in its own
    ISOLATED directory ``base_dir/worker{i}/`` — there is no shared
    journal directory anywhere in the fleet; ``base_dir`` is merely
    where this (single-machine) launcher happens to put the private
    dirs. ``config_args`` select the model (e.g. ``["--preset",
    "test-tiny"]``); ``engine_args`` are pool/page knobs; ``tiers``
    assigns a disaggregation role per worker (None = all mixed)."""
    if tiers is not None:
        assert len(tiers) == n_workers, (tiers, n_workers)
    return [make_worker_spec(
        i, os.path.join(base_dir, f"worker{i}"), config_args,
        engine_args, env,
        tier=(tiers[i] if tiers else "mixed"))
        for i in range(n_workers)]


def worker_spec_factory(base_dir: str, config_args: List[str],
                        engine_args: Optional[List[str]] = None,
                        env: Optional[dict] = None
                        ) -> Callable[[int], WorkerSpec]:
    """The autoscaler's spec source: ``factory(idx)`` yields a spec in
    a fresh private dir, same shape as the initial fleet's."""
    def factory(idx: int) -> WorkerSpec:
        return make_worker_spec(
            idx, os.path.join(base_dir, f"worker{idx}"), config_args,
            engine_args, env)
    return factory


def spawn_fleet(specs: List[WorkerSpec], rcfg=None, scfg=None,
                telemetry=None, clock=time.monotonic,
                wait: bool = True, autoscale=None, spec_factory=None,
                listen_host: str = "127.0.0.1"):
    """Launch the out-of-process fleet: one supervisor over ``specs``,
    one Router over :class:`~..serve.router.RemoteReplica` backends,
    wired together (``router.supervisor`` set, chaos delegated).
    Workers register over RPC — the router holds NO worker paths.
    Returns ``(router, supervisor)``; callers own shutdown
    (``supervisor.stop_all()`` then ``router.close()``)."""
    from ..serve.router import RemoteReplica, Router, RouterConfig
    rcfg = rcfg or RouterConfig(n_replicas=len(specs))
    scfg = scfg or SupervisorConfig()
    backends = [RemoteReplica(s.idx, None,
                              rpc_timeout_s=rcfg.step_timeout_s,
                              step_timeout_s=rcfg.step_timeout_s)
                for s in specs]
    router = Router(rcfg=rcfg, backends=backends, telemetry=telemetry,
                    clock=clock)
    sup = ProcSupervisor(specs, scfg, autoscale=autoscale,
                         spec_factory=spec_factory,
                         listen_host=listen_host)
    sup.attach_router(router)
    sup.start_all(wait=wait)
    return router, sup
