"""Process supervisor: restart-on-exit, backoff, quarantine, rolling
restarts — and real process-level chaos for the multi-process fleet.

PR 4's supervision heals *inside* a process (rollback, watchdog,
shedding); PR 8's router heals *across* in-process replicas. This
module closes the last gap: the replicas are now worker **processes**
(serve/worker.py), and something must notice when one of them actually
dies. The supervisor owns that policy; the router
(serve/router.py) owns the request ledger. The split is deliberate —
the router decides what happens to *requests* (keep waiting for a
restart, requeue onto survivors), the supervisor decides what happens
to *processes* (restart with backoff, give up and quarantine):

- **Death detection**: ``Popen.poll`` per tick, plus periodic RPC
  ``health`` probes with short timeouts (a zombie that holds its port
  but answers nothing is as dead as an exited one — two consecutive
  probe failures escalate to SIGKILL so the exit path takes over).
- **Restart-on-exit**: an unexpected exit marks the replica down in
  the router (its in-flight ledger entries WAIT — the restarted worker
  replays its journal and resumes them), then respawns after an
  exponential backoff (``backoff_s * backoff_mult^n``). Each spawn
  writes a fresh generation into the worker's ready file; the
  supervisor attaches the router only when the ready file shows the
  generation it launched.
- **Restart budget → quarantine**: past ``restart_budget`` *crash*
  restarts (intentional rolling-restart stops are free), the
  supervisor stops trying: ``Router.abandon_replica`` requeues the
  worker's journaled in-flight work onto the survivors and the
  replica leaves rotation for good.
- **Rolling restart**: replica by replica — drain (the router
  migrates its in-flight requests onto the rest of the fleet), stop
  gracefully (``shutdown`` RPC, SIGTERM fallback), respawn, wait
  attached, move on. At least ``n-1`` workers serve at every moment,
  so a fleet of two or more drops nothing; ``/readyz`` reports 503
  exactly when zero routable warmed workers remain.
- **Chaos**: ``proc_kill`` (a real ``SIGKILL`` — no Python cleanup,
  no flushed buffers, the fault every other layer only simulated) and
  ``proc_hang`` (``SIGSTOP`` for N ticks, then ``SIGCONT`` — the
  process is alive but frozen, which the router's RPC timeouts and
  wedge probe must survive). Both arrive through the standard
  ``FaultPlan`` machinery: ``Router.step`` fires the ``fleet/step``
  seam and delegates the proc kinds here (faults/fleet.py).

Everything is ticked from the same single-threaded loop that steps the
router (the HTTP driver task, or the fleet replay loop): one
``supervisor.tick()`` after each ``router.step()``. No threads, no
signals-as-control-flow — deaths are observed, never raced.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional

#: handle lifecycle states
RUNNING = "running"
BACKOFF = "backoff"
SPAWNING = "spawning"       # process launched, ready file not seen yet
QUARANTINED = "quarantined"
STOPPED = "stopped"


@dataclass(frozen=True)
class SupervisorConfig:
    """Restart policy knobs (docs/robustness.md has the fault matrix)."""

    #: crash restarts per worker before quarantine (intentional
    #: rolling-restart stops do not count)
    restart_budget: int = 3
    backoff_s: float = 0.5
    backoff_mult: float = 2.0
    #: a spawned worker must write its ready file within this budget
    #: (covers jax import + compile warmup) or the spawn counts as a
    #: crash
    ready_timeout_s: float = 180.0
    #: RPC health-probe budget; two consecutive failures escalate to
    #: SIGKILL
    probe_timeout_s: float = 2.0
    #: probe every N ticks (0 disables probing — the router's own step
    #: RPC failures still catch deaths)
    probe_every: int = 8


@dataclass
class WorkerSpec:
    """How to (re)launch one worker. ``cmd`` is the full command minus
    the per-spawn ``--gen``; the supervisor appends that."""

    idx: int
    cmd: List[str]
    journal_path: str
    ready_file: str
    log_path: Optional[str] = None
    env: Optional[dict] = None


@dataclass
class WorkerHandle:
    spec: WorkerSpec
    proc: Optional[subprocess.Popen] = None
    state: str = STOPPED
    gen: int = -1
    pid: Optional[int] = None
    restarts: int = 0          # every respawn (rolling included)
    crash_restarts: int = 0    # budget-counted respawns
    backoff_until: float = 0.0
    spawn_t: float = 0.0
    hang_ticks: int = 0        # SIGSTOP chaos: SIGCONT when it hits 0
    probe_failures: int = 0
    intentional_stop: bool = False
    events: List[str] = field(default_factory=list)


class ProcSupervisor:
    """Owns the worker processes of one fleet. Drive it with
    :meth:`tick` from the router's loop; it talks back to the router
    through ``mark_down`` / ``attach_replica`` / ``abandon_replica``.
    """

    def __init__(self, specs: List[WorkerSpec],
                 cfg: SupervisorConfig = SupervisorConfig()):
        self.cfg = cfg
        self.handles = [WorkerHandle(spec=s) for s in specs]
        self.router = None          # attach_router
        self.ticks = 0
        self._rolling: List[int] = []
        self._rolling_phase = ""
        self._rolling_target_gen = -1
        self.events: List[str] = []

    def attach_router(self, router) -> None:
        self.router = router
        router.supervisor = self

    @property
    def reviving(self) -> bool:
        """True while any worker is on its way back (spawning, backing
        off, or intentionally stopped for a rolling restart) — the
        router's requeue ladder holds its retry budget while this is
        set instead of burning attempts against a fleet that is mid-
        recovery (a zero-routable window during a single-worker rolling
        restart must not reject the held requests)."""
        return any(h.state in (SPAWNING, BACKOFF) or h.intentional_stop
                   for h in self.handles)

    # ------------------------------------------------------------- spawn

    def _event(self, msg: str) -> None:
        self.events.append(msg)
        if len(self.events) > 256:
            del self.events[:len(self.events) - 256]
        if self.router is not None:
            from ..utils.telemetry import ROUTER_TRACK
            self.router._event(f"supervisor: {msg}")
            self.router.tel.instant("supervisor", ROUTER_TRACK,
                                    note=msg)

    def _spawn(self, h: WorkerHandle) -> None:
        h.gen += 1
        h.restarts += int(h.gen > 0)
        try:
            os.remove(h.spec.ready_file)
        except OSError:
            pass
        stdout = subprocess.DEVNULL
        if h.spec.log_path:
            stdout = open(h.spec.log_path, "a")
        env = {**os.environ, **(h.spec.env or {})}
        h.proc = subprocess.Popen(
            h.spec.cmd + ["--gen", str(h.gen)],
            stdout=stdout, stderr=stdout, env=env)
        if stdout is not subprocess.DEVNULL:
            stdout.close()      # Popen holds its own dup
        h.pid = h.proc.pid
        h.state = SPAWNING
        h.spawn_t = time.monotonic()
        h.probe_failures = 0
        self._event(f"worker {h.spec.idx} spawned "
                    f"(pid {h.pid}, gen {h.gen})")

    def start_all(self, wait: bool = True,
                  timeout_s: Optional[float] = None) -> None:
        """Spawn every worker; with ``wait`` (the default), block until
        each one is ready and attached to the router. A failed (or
        interrupted) startup stops EVERY spawned worker before raising
        — an orphaned worker would hold its journal flock and crash-
        loop the next run's replacement with JournalBusyError."""
        for h in self.handles:
            self._spawn(h)
        if not wait:
            return
        budget = timeout_s or self.cfg.ready_timeout_s
        deadline = time.monotonic() + budget
        try:
            while time.monotonic() < deadline:
                for h in self.handles:
                    if h.state == SPAWNING:
                        self._check_ready(h)
                    elif (h.state == BACKOFF
                          and time.monotonic() >= h.backoff_until):
                        # a worker that crashed during startup retries
                        # inside the wait (the tick loop is not running
                        # yet) — without this, one startup crash burns
                        # the whole ready budget
                        self._spawn(h)
                if all(h.state == RUNNING for h in self.handles):
                    return
                if any(h.state == QUARANTINED for h in self.handles):
                    break          # crash-looped out of the budget:
                    #                fail fast, don't burn the deadline
                time.sleep(0.05)
        except BaseException:      # Ctrl-C mid-warmup included
            self.stop_all()
            raise
        bad = [h.spec.idx for h in self.handles if h.state != RUNNING]
        logs = [self.handles[i].spec.log_path for i in bad]
        self.stop_all()
        raise RuntimeError(
            f"workers {bad} not ready within {budget}s (see {logs})")

    def stop_all(self, timeout_s: float = 15.0) -> None:
        for h in self.handles:
            h.intentional_stop = True
            h.state = STOPPED
            if h.proc is not None and h.proc.poll() is None:
                if h.hang_ticks:          # a stopped process cannot
                    self._signal(h, signal.SIGCONT)   # handle SIGTERM
                    h.hang_ticks = 0
                self._signal(h, signal.SIGTERM)
        deadline = time.monotonic() + timeout_s
        for h in self.handles:
            if h.proc is None:
                continue
            while (h.proc.poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            if h.proc.poll() is None:
                self._signal(h, signal.SIGKILL)
                h.proc.wait()

    @staticmethod
    def _signal(h: WorkerHandle, sig) -> None:
        try:
            os.kill(h.proc.pid, sig)
        except (OSError, AttributeError):
            pass

    # -------------------------------------------------------------- tick

    def tick(self) -> None:
        """One supervision pass: resume chaos hangs, observe deaths,
        advance backoffs/spawns, probe health, advance any rolling
        restart. Call after every ``router.step()`` (and on idle loop
        iterations — restarts must progress while the fleet waits)."""
        router = self.router
        assert router is not None, "attach_router first"
        self.ticks += 1
        for h in self.handles:
            if h.hang_ticks > 0:
                h.hang_ticks -= 1
                if h.hang_ticks == 0:
                    self._signal(h, signal.SIGCONT)
                    self._event(f"worker {h.spec.idx} SIGCONT "
                                f"(hang over)")
            if h.state == RUNNING:
                if h.proc is not None and h.proc.poll() is not None:
                    self._on_exit(h, h.proc.returncode)
                    continue
                self._maybe_probe(h)
                # the router declared it down (RPC refused / worker
                # dispatch broken) but the process lingers: a zombie —
                # SIGKILL it so the exit path owns recovery
                if (not router.replicas[h.spec.idx].alive
                        and h.hang_ticks == 0):
                    self._event(f"worker {h.spec.idx} unreachable but "
                                f"process alive — escalating SIGKILL")
                    self._signal(h, signal.SIGKILL)
            elif h.state == BACKOFF:
                if time.monotonic() >= h.backoff_until:
                    self._spawn(h)
            elif h.state == SPAWNING:
                self._check_ready(h)
        self._tick_rolling()

    def _on_exit(self, h: WorkerHandle, rc) -> None:
        router = self.router
        router.mark_down(h.spec.idx,
                         f"process exited rc={rc}")
        if h.intentional_stop:
            # rolling restart / operator stop: free respawn, no budget
            h.intentional_stop = False
            self._event(f"worker {h.spec.idx} stopped (intentional); "
                        f"respawning")
            self._spawn(h)
            return
        h.crash_restarts += 1
        if h.crash_restarts > self.cfg.restart_budget:
            h.state = QUARANTINED
            self._event(f"worker {h.spec.idx} exceeded restart budget "
                        f"({self.cfg.restart_budget}); quarantined — "
                        f"requeueing its journal onto survivors")
            router.abandon_replica(h.spec.idx)
            return
        delay = (self.cfg.backoff_s
                 * self.cfg.backoff_mult ** (h.crash_restarts - 1))
        h.state = BACKOFF
        h.backoff_until = time.monotonic() + delay
        self._event(f"worker {h.spec.idx} died rc={rc}; restart "
                    f"{h.crash_restarts}/{self.cfg.restart_budget} in "
                    f"{delay:.2f}s")

    def _check_ready(self, h: WorkerHandle) -> None:
        router = self.router
        if h.proc is not None and h.proc.poll() is not None:
            # died during startup — counts as a crash
            h.state = RUNNING   # route through the common exit path
            self._on_exit(h, h.proc.returncode)
            return
        doc = self._read_ready(h.spec.ready_file)
        if doc is not None and doc.get("gen") == h.gen:
            try:
                info = router.attach_replica(
                    h.spec.idx, int(doc["port"]),
                    pid=int(doc["pid"]), gen=h.gen)
                router.replicas[h.spec.idx].restarts = h.restarts
            except Exception as e:  # noqa: BLE001 — a worker dying
                # between ready-file write and attach is a crash like
                # any other; fold it into the exit path next tick
                self._event(f"worker {h.spec.idx} attach failed: {e}")
                self._signal(h, signal.SIGKILL)
                return
            h.state = RUNNING
            self._event(f"worker {h.spec.idx} ready+attached "
                        f"(gen {h.gen}, kept {info['kept']}, "
                        f"requeued {info['requeued']}, "
                        f"ghosts {info['ghosts']})")
            return
        if (time.monotonic() - h.spawn_t
                > self.cfg.ready_timeout_s):
            self._event(f"worker {h.spec.idx} missed ready deadline; "
                        f"killing")
            self._signal(h, signal.SIGKILL)

    @staticmethod
    def _read_ready(path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _maybe_probe(self, h: WorkerHandle) -> None:
        if (self.cfg.probe_every <= 0
                or self.ticks % self.cfg.probe_every
                or h.hang_ticks > 0):   # a chaos-hung worker is
            return                      # *supposed* to be unresponsive
        rep = self.router.replicas[h.spec.idx]
        try:
            rep.client.call("health",
                            timeout_s=self.cfg.probe_timeout_s)
            h.probe_failures = 0
        except Exception:  # noqa: BLE001 — timeout, refusal, garbage:
            # the probe only counts failures, the escalation decides
            h.probe_failures += 1
            if h.probe_failures >= 2:
                self._event(f"worker {h.spec.idx} failed "
                            f"{h.probe_failures} health probes; "
                            f"escalating SIGKILL")
                self._signal(h, signal.SIGKILL)

    # ------------------------------------------------------------- chaos

    def chaos_kill(self, idx: int) -> None:
        """``proc_kill``: a real SIGKILL — no cleanup, no flushes."""
        h = self.handles[idx]
        self._event(f"CHAOS proc_kill worker {idx} (pid {h.pid})")
        self._signal(h, signal.SIGKILL)

    def chaos_hang(self, idx: int, ticks: int) -> None:
        """``proc_hang``: SIGSTOP now, SIGCONT after ``ticks`` ticks."""
        h = self.handles[idx]
        self._event(f"CHAOS proc_hang worker {idx} for {ticks} ticks")
        h.hang_ticks = max(int(ticks), 1)
        self._signal(h, signal.SIGSTOP)

    # --------------------------------------------------- rolling restart

    @property
    def rolling_active(self) -> bool:
        return bool(self._rolling)

    def start_rolling_restart(self) -> None:
        """Queue a drain -> stop -> respawn -> reattach cycle over every
        worker, one at a time (ticked forward by :meth:`tick`)."""
        if self._rolling:
            return
        self._rolling = [h.spec.idx for h in self.handles
                         if h.state != QUARANTINED]
        self._rolling_phase = "drain"
        self._event(f"rolling restart of workers {self._rolling}")

    def _tick_rolling(self) -> None:
        if not self._rolling:
            return
        router = self.router
        idx = self._rolling[0]
        h = self.handles[idx]
        if self._rolling_phase == "drain":
            router.drain_replica(idx)
            h.intentional_stop = True
            #: advance only once THIS generation is gone and the NEXT
            #: one is attached — "running and alive" is already true in
            #: the instant after the shutdown RPC (the worker takes a
            #: moment to exit), and advancing on it would drain the
            #: whole fleet at once
            self._rolling_target_gen = h.gen + 1
            rep = router.replicas[idx]
            try:
                rep.client.call("drain", timeout_s=2.0)
                rep.client.call("shutdown", timeout_s=2.0)
            except Exception:  # noqa: BLE001 — graceful path failed;
                # SIGTERM says the same thing louder
                self._signal(h, signal.SIGTERM)
            self._rolling_phase = "await_restart"
        elif self._rolling_phase == "await_restart":
            if (h.gen >= self._rolling_target_gen
                    and h.state == RUNNING
                    and router.replicas[idx].alive):
                self._rolling.pop(0)
                self._rolling_phase = "drain"
                if not self._rolling:
                    self._event("rolling restart complete")
            elif h.state == QUARANTINED:
                # it crashed its way out of the budget mid-restart —
                # abandon the rolling pass for this worker
                self._rolling.pop(0)
                self._rolling_phase = "drain"


# -------------------------------------------------------------- builders

def make_worker_specs(n_workers: int, journal_dir: str,
                      config_args: List[str],
                      engine_args: Optional[List[str]] = None,
                      env: Optional[dict] = None) -> List[WorkerSpec]:
    """Specs for N ``serve-worker`` subprocesses sharing one journal
    directory (worker{i}.jsonl + worker{i}.ready.json + worker{i}.log).
    ``config_args`` select the model (e.g. ``["--preset",
    "test-tiny"]``); ``engine_args`` are pool/page knobs."""
    os.makedirs(journal_dir, exist_ok=True)
    # the workers must import THIS package regardless of the caller's
    # cwd (`python -m` resolves against the child's sys.path, and the
    # repo is not necessarily pip-installed)
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(env or {})
    env.setdefault("PYTHONPATH", os.pathsep.join(
        p for p in (pkg_root, os.environ.get("PYTHONPATH")) if p))
    specs = []
    for i in range(n_workers):
        jpath = os.path.join(journal_dir, f"worker{i}.jsonl")
        ready = os.path.join(journal_dir, f"worker{i}.ready.json")
        log = os.path.join(journal_dir, f"worker{i}.log")
        cmd = [sys.executable, "-m", "replicatinggpt_tpu",
               "serve-worker", *config_args,
               "--port", "0", "--journal", jpath,
               "--ready-file", ready, *(engine_args or [])]
        specs.append(WorkerSpec(idx=i, cmd=cmd, journal_path=jpath,
                                ready_file=ready, log_path=log,
                                env=env))
    return specs


def spawn_fleet(specs: List[WorkerSpec], rcfg=None, scfg=None,
                telemetry=None, clock=time.monotonic,
                wait: bool = True):
    """Launch the out-of-process fleet: one supervisor over ``specs``,
    one Router over :class:`~..serve.router.RemoteReplica` backends,
    wired together (``router.supervisor`` set, chaos delegated).
    Returns ``(router, supervisor)``; callers own shutdown
    (``supervisor.stop_all()`` then ``router.close()``)."""
    from ..serve.router import RemoteReplica, Router, RouterConfig
    rcfg = rcfg or RouterConfig(n_replicas=len(specs))
    scfg = scfg or SupervisorConfig()
    backends = [RemoteReplica(s.idx, s.journal_path,
                              rpc_timeout_s=rcfg.step_timeout_s,
                              step_timeout_s=rcfg.step_timeout_s)
                for s in specs]
    router = Router(rcfg=rcfg, backends=backends, telemetry=telemetry,
                    clock=clock)
    sup = ProcSupervisor(specs, scfg)
    sup.attach_router(router)
    sup.start_all(wait=wait)
    return router, sup
