"""Serve self-healing policies: stall watchdog, speculative auto-disable,
load shedding.

These are small host-side policy objects the serving engine
(serve/engine.py) consults once per step — pure bookkeeping over values
the engine already has (step wall time, queue depth, per-step
draft/accept counts), so an all-off :class:`ResilienceConfig` (the
default) adds nothing to the step path and changes no existing
behavior. Every recovery decision lands in the engine's Metrics
(``watchdog_stalls``, ``spec_disables``, ``spec_reprobes``,
``shed_requests``) and the degraded transitions stay inside the
already-compiled program set: disabling speculation switches the engine
from its verify jit to its decode jit (both CompileGuard-budgeted at
one program), never to a new shape.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Optional, Tuple

from ..utils.telemetry import NULL


@dataclass(frozen=True)
class ResilienceConfig:
    """Engine self-healing knobs; every subsystem is opt-in (0 = off).

    - watchdog: a step is a *stall* when its wall time exceeds
      ``max(stall_factor x running p99, stall_floor_s)`` after
      ``stall_min_steps`` samples. Detection + counters (a synchronous
      engine cannot preempt a wedged device call; the watchdog's job is
      to make the stall visible and flip the engine into degraded
      mode so shedding kicks in while it lasts);
    - speculative auto-disable: when the windowed accept rate over
      ``spec_window`` slot-steps drops below ``spec_disable_threshold``
      the drafter is a pure tax — disable it, re-probe after
      ``spec_reprobe_after`` steps (backing off ``spec_reprobe_backoff``x
      per consecutive failed probe, capped);
    - load shedding: queue depth above ``shed_watermark x max_queue``
      for ``shed_patience`` consecutive steps sheds the newest queued
      requests back down to the watermark (the oldest are closest to
      service; fresh arrivals are the cheapest to turn away).
    """

    stall_factor: float = 0.0
    stall_floor_s: float = 0.05
    stall_min_steps: int = 20
    stall_skip_steps: int = 2     # warmup laps excluded from the window
                                  # (the first steps carry XLA compiles)
    spec_disable_threshold: float = 0.0
    spec_window: int = 16
    spec_reprobe_after: int = 32
    spec_reprobe_backoff: float = 2.0
    spec_reprobe_cap: int = 1024
    shed_watermark: float = 0.0
    shed_patience: int = 4

    @property
    def watchdog_on(self) -> bool:
        return self.stall_factor > 0

    @property
    def spec_guard_on(self) -> bool:
        return self.spec_disable_threshold > 0

    @property
    def shed_on(self) -> bool:
        return self.shed_watermark > 0


#: detection-only defaults for bench/replay runs: stall visibility and
#: speculative auto-disable on, shedding off (shedding changes the
#: workload a bench measures; enable it deliberately)
DEFAULT_SERVE_RESILIENCE = ResilienceConfig(stall_factor=4.0,
                                            spec_disable_threshold=0.125)


class StepWatchdog:
    """p99-budget stall detector over step wall times (bounded window).

    ``telemetry`` (utils.telemetry) marks every detected stall as an
    instant on the engine timeline — recovery events sit next to the
    step spans they interrupted, instead of only incrementing a
    counter someone reads after the run."""

    def __init__(self, cfg: ResilienceConfig, window: int = 512,
                 telemetry=None):
        self.cfg = cfg
        self.laps: Deque[float] = deque(maxlen=window)
        self._skip = cfg.stall_skip_steps
        self.tel = telemetry or NULL

    def observe(self, dur_s: float) -> bool:
        """Record one step's wall time; True when it was a stall."""
        if self._skip > 0:
            # warmup laps carry one-time XLA compiles — seconds against
            # a millisecond steady state; letting them into the window
            # would inflate the p99 budget ~1000x and blind the watchdog
            self._skip -= 1
            return False
        stall = False
        if len(self.laps) >= self.cfg.stall_min_steps:
            laps = sorted(self.laps)
            p99 = laps[min(int(0.99 * (len(laps) - 1) + 0.5),
                           len(laps) - 1)]
            budget = max(self.cfg.stall_factor * p99,
                         self.cfg.stall_floor_s)
            stall = dur_s > budget
            if stall:
                self.tel.instant("watchdog_stall", dur_ms=dur_s * 1e3,
                                 budget_ms=budget * 1e3)
        # the stalled lap still enters the window (a persistently slow
        # engine raises its own budget rather than alarming forever)
        self.laps.append(dur_s)
        return stall


class SpecHealth:
    """Windowed accept-rate monitor driving speculative auto-disable.

    The engine reports (drafted, accepted) after every verify step;
    :meth:`observe` returns True when the drafter should be disabled.
    While disabled, :meth:`tick_disabled` counts down to the next
    re-probe (exponential backoff across consecutive failed probes).
    Acceptance-exactness means a bad drafter can never corrupt output —
    the only thing at stake is throughput, so the policy optimizes
    purely for that."""

    def __init__(self, cfg: ResilienceConfig, telemetry=None):
        self.cfg = cfg
        self.window: Deque[Tuple[int, int]] = deque(maxlen=cfg.spec_window)
        self.cooldown = 0
        self._next_cooldown = cfg.spec_reprobe_after
        self.tel = telemetry or NULL

    def observe(self, drafted: int, accepted: int) -> bool:
        self.window.append((drafted, accepted))
        if len(self.window) < self.cfg.spec_window:
            return False
        tot_d = sum(d for d, _ in self.window)
        if tot_d < self.cfg.spec_window:      # too few proposals to judge
            return False
        rate = sum(a for _, a in self.window) / tot_d
        return rate < self.cfg.spec_disable_threshold

    def on_disable(self) -> None:
        self.tel.instant("spec_disable", cooldown=self._next_cooldown)
        self.window.clear()
        self.cooldown = self._next_cooldown
        self._next_cooldown = min(
            int(self._next_cooldown * self.cfg.spec_reprobe_backoff),
            self.cfg.spec_reprobe_cap)

    def on_reenable(self) -> None:
        """A probe survived a full window: the drafter is healthy again —
        reset the backoff."""
        self.tel.instant("spec_probe_healthy")
        self._next_cooldown = self.cfg.spec_reprobe_after

    def tick_disabled(self) -> bool:
        """One disabled step; True when it is time to re-probe."""
        self.cooldown -= 1
        if self.cooldown <= 0:
            self.tel.instant("spec_reprobe")
            return True
        return False


class LoadShedder:
    """Sustained-overload detector: queue depth over the watermark for
    ``shed_patience`` consecutive steps -> shed down to the watermark."""

    def __init__(self, cfg: ResilienceConfig, telemetry=None):
        self.cfg = cfg
        self.streak = 0
        self.tel = telemetry or NULL

    def observe(self, depth: int, max_queue: int) -> int:
        """Returns how many queued requests to shed this step (0 almost
        always)."""
        watermark = int(self.cfg.shed_watermark * max_queue)
        if depth > watermark:
            self.streak += 1
        else:
            self.streak = 0
            return 0
        if self.streak < self.cfg.shed_patience:
            return 0
        self.tel.instant("load_shed", n=depth - watermark, depth=depth)
        return depth - watermark
