"""Network chaos: deterministic message-level faults on the fleet RPC.

faults/fleet.py can kill, wedge, or SIGSTOP a *process*; nothing there
can hurt a *message*. Every exactly-once claim the fleet makes (journal
replay, requeue ladders, disagg transfers) therefore rode on an
implicitly clean pipe between router and worker. This module closes
that gap: a :class:`FaultyTransport` wraps the router's
:class:`~..serve.rpc.RpcClient` and consults the installed
:class:`~.inject.FaultPlan` before (and around) every call, injecting
the seven wire-fault kinds real multi-host fleets see first —
half-open links, duplicated retries, reordered frames, asymmetric
partitions. Same design contract as faults/inject.py: no-op by default
(one module-global read per call), deterministic (per-link-per-verb
call ordinals, never wall-clock races), and injected faults are
indistinguishable from real ones (a dropped frame raises the same
:class:`~..serve.rpc.RpcTimeout` a SIGSTOP'd worker does).

Sites are ``net/{src}->{dst}/{verb}`` — e.g.
``net/router->worker1/submit``. :func:`net_call_fault` tries the
spellings most-specific-first, so one plan can target one verb on one
link, every verb on one link (``net/router->worker1/*``), one verb
fleet-wide (``net/*->*/submit``), or everything (:data:`NET_CALL`).
The index passed to the plan is always the transport's own per-verb
call ordinal on that link, so ``Fault(at=2, times=3)`` means "calls
2..4 of that verb on that link" under every spelling.

Kinds (the network fault matrix — docs/robustness.md):

==================  =====================================================
kind                effect at the transport
==================  =====================================================
``net_delay``       sleep ``arg`` seconds, then send normally
``net_drop``        the request frame is lost: nothing is sent, the
                    caller sees :class:`RpcTimeout` (maybe-executed —
                    indistinguishable from a hung worker)
``net_dup``         the frame is sent TWICE with the same idempotency
                    key; the caller gets the second response (the
                    worker's cached reply, ``idem_hit``) — only calls
                    that carry an ``idem`` key can be duplicated
``net_reorder``     the link's PREVIOUS idempotent frame is re-sent
                    first (a stale duplicate arriving late); its
                    response is discarded through the observer, then
                    the current call proceeds normally
``net_trickle``     the frame drips onto the wire in ``arg``-byte
                    chunks with ``arg2`` seconds between chunks
``net_corrupt``     one byte of the request frame BODY is flipped
                    (seeded); the far side's checksum rejects it with a
                    typed protocol error and the stream is poisoned —
                    never a mis-decoded result
``net_partition``   ``arg2 == 0``: two-way — the call fails
                    :class:`RpcDown` without touching the wire.
                    ``arg2 == 1``: one-way — the request EXECUTES but
                    the response is lost (:class:`RpcTimeout`, the
                    maybe-executed case). ``times`` is the partition
                    width in calls; the first clean call after is the
                    heal edge
==================  =====================================================

The ``observer`` (the router's :class:`~..serve.router.RemoteReplica`)
hears two things: ``net_chaos_response(resp)`` for responses the chaos
layer swallowed (reorder/one-way partition) — so duplicate-suppression
accounting sees EVERY response, even discarded ones — and
``net_chaos_partition(active)`` on partition enter/heal edges, which
the router turns into the ``rpc_partitions_active`` counter and the
``net_partition``/``net_heal`` trace instants. ``dups_injected``
counts every duplicate frame this transport actually put on the wire;
the chaos soak asserts ``rpc_dup_suppressed`` equals its fleet-wide
sum exactly.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from .inject import Fault, active

#: lazily-bound serve.rpc module: importing it pulls the serve package
#: (and jax with it), and the faults package must stay importable from
#: jax-free contexts (procsup's contract) until a transport actually
#: exists — by which point the serve package is loaded anyway
_RPC = None


def _rpc():
    global _RPC
    if _RPC is None:
        from ..serve import rpc
        _RPC = rpc
    return _RPC

#: the catch-all site: matches every verb on every link (tried last)
NET_CALL = "net/call"

KIND_NET_DELAY = "net_delay"
KIND_NET_DROP = "net_drop"
KIND_NET_DUP = "net_dup"
KIND_NET_REORDER = "net_reorder"
KIND_NET_TRICKLE = "net_trickle"
KIND_NET_CORRUPT = "net_corrupt"
KIND_NET_PARTITION = "net_partition"

NET_KINDS = (KIND_NET_DELAY, KIND_NET_DROP, KIND_NET_DUP,
             KIND_NET_REORDER, KIND_NET_TRICKLE, KIND_NET_CORRUPT,
             KIND_NET_PARTITION)


def net_site(src: str, dst: str, verb: str) -> str:
    """The canonical site string for one (link, verb)."""
    return f"net/{src}->{dst}/{verb}"


def net_call_fault(src: str, dst: str, verb: str,
                   index: int) -> Optional[Fault]:
    """Ask the installed plan for a fault on this call, trying site
    spellings most-specific-first. The index is the per-link-per-verb
    call ordinal under EVERY spelling (deterministic regardless of how
    broadly the plan targeted)."""
    plan = active()
    if plan is None:
        return None
    for site in (net_site(src, dst, verb), net_site(src, dst, "*"),
                 net_site("*", "*", verb), NET_CALL):
        f = plan.fire(site, index=index)
        if f is not None:
            return f
    return None


class FaultyTransport:
    """Chaos-injecting wrapper with the :class:`~..serve.rpc.RpcClient`
    call surface. ALWAYS wrapped around the router's clients
    (:meth:`~..serve.router.RemoteReplica.connect`): with no plan
    installed, :meth:`call` is one module-global read and a straight
    delegate — tier-1 RPC behavior stays byte-identical."""

    def __init__(self, client, src: str, dst: str, observer=None):
        self.client = client
        self.src = src
        self.dst = dst
        #: the router-side replica proxy: hears discarded responses and
        #: partition enter/heal edges (both optional, getattr-guarded)
        self.observer = observer
        #: duplicate frames actually put on the wire (dup + reorder) —
        #: the soak's ground truth for ``rpc_dup_suppressed``
        self.dups_injected = 0
        self.partitioned = False
        self._counts: Dict[str, int] = {}
        #: (op, timeout_s, kwargs) of the last idem-carrying call — the
        #: frame ``net_reorder`` replays out of order
        self._last_idem: Optional[Tuple[str, Optional[float],
                                        dict]] = None

    # ------------------------------------------------- client delegation

    @property
    def host(self):
        return self.client.host

    @property
    def port(self):
        return self.client.port

    @property
    def timeout_s(self):
        return self.client.timeout_s

    def connect(self) -> None:
        self.client.connect()

    def close(self) -> None:
        self.client.close()

    # --------------------------------------------------------- the seam

    def call(self, op: str, timeout_s: Optional[float] = None,
             **kwargs) -> dict:
        if active() is None:       # the no-chaos fast path
            return self.client.call(op, timeout_s=timeout_s, **kwargs)
        idx = self._counts.get(op, 0)
        self._counts[op] = idx + 1
        f = net_call_fault(self.src, self.dst, op, idx)
        if f is not None and f.kind == KIND_NET_PARTITION:
            return self._partitioned_call(f, op, timeout_s, kwargs)
        if self.partitioned:
            self._set_partitioned(False)   # first clean call: the heal
        if f is None:
            return self._send(op, timeout_s, kwargs)
        if f.kind == KIND_NET_DELAY:
            time.sleep(f.arg or 0.05)  # graftlint: disable=GL019 — chaos injection: the delay IS the fault
            return self._send(op, timeout_s, kwargs)
        if f.kind == KIND_NET_DROP:
            # the frame dies on the wire: nothing sent, and the caller
            # cannot know whether the worker executed — exactly what a
            # real lost frame looks like, so raise the maybe-executed
            # failure, not the definitely-dead one
            self.client.close()
            raise _rpc().RpcTimeout(f"{op}: frame dropped (chaos)")
        if f.kind == KIND_NET_DUP:
            return self._dup(op, timeout_s, kwargs)
        if f.kind == KIND_NET_REORDER:
            self._reorder()
            return self._send(op, timeout_s, kwargs)
        if f.kind == KIND_NET_TRICKLE:
            return self._trickle(f, op, timeout_s, kwargs)
        if f.kind == KIND_NET_CORRUPT:
            return self._corrupt(f, op, timeout_s, kwargs)
        raise ValueError(f"unknown net fault kind {f.kind!r}")

    # ----------------------------------------------------- kind payloads

    def _send(self, op: str, timeout_s: Optional[float],
              kwargs: dict) -> dict:
        if "idem" in kwargs:
            self._last_idem = (op, timeout_s, dict(kwargs))
        return self.client.call(op, timeout_s=timeout_s, **kwargs)

    def _dup(self, op: str, timeout_s: Optional[float],
             kwargs: dict) -> dict:
        """Send the frame twice with the SAME idempotency key and hand
        the caller the second response — the worker's cached reply.
        Calls without an idem key cannot be safely duplicated (there is
        nothing to suppress the re-execution), so the fault degrades to
        a normal send there."""
        if "idem" not in kwargs:
            return self._send(op, timeout_s, kwargs)
        self._send(op, timeout_s, kwargs)       # the original
        self.dups_injected += 1
        return self.client.call(op, timeout_s=timeout_s, **kwargs)

    def _reorder(self) -> None:
        """Replay the link's previous idempotent frame ahead of the
        current one — a stale duplicate arriving out of order. Its
        response (the worker's cached reply) is discarded through the
        observer so suppression accounting still sees it. No history
        yet means nothing to reorder."""
        if self._last_idem is None:
            return
        prev_op, prev_to, prev_kw = self._last_idem
        try:
            stale = self.client.call(prev_op, timeout_s=prev_to,
                                     **prev_kw)
        except _rpc().RpcError:
            return                  # the stale frame died en route
        self.dups_injected += 1
        self._observe_response(stale)

    def _trickle(self, f: Fault, op: str, timeout_s: Optional[float],
                 kwargs: dict) -> dict:
        """Drip the frame onto the wire in tiny chunks — a congested or
        deliberately slow link. The far side must assemble the frame
        from however the segments land (the _recv_exact loops)."""
        self.client.send_chunking = (max(int(f.arg), 1) or 3,
                                     float(f.arg2) or 0.002)
        try:
            return self._send(op, timeout_s, kwargs)
        finally:
            self.client.send_chunking = None

    def _corrupt(self, f: Fault, op: str, timeout_s: Optional[float],
                 kwargs: dict) -> dict:
        """Flip one seeded byte in the request frame's BODY (never the
        length prefix — a corrupt length desyncs framing nondeterminis-
        tically; a corrupt body is exactly what the checksum exists to
        catch). The far side answers a typed protocol error; the
        caller's retry-once path re-sends with the same idem key."""
        plan = active()
        rng = (plan.rng(net_site(self.src, self.dst, op))
               if plan is not None else None)

        def flip(frame: bytes) -> bytes:
            HEADER_BYTES = _rpc().HEADER_BYTES
            if len(frame) <= HEADER_BYTES:
                return frame
            off = HEADER_BYTES + (int(rng.integers(
                0, len(frame) - HEADER_BYTES)) if rng is not None else 0)
            return (frame[:off] + bytes([frame[off] ^ 0xFF])
                    + frame[off + 1:])

        self.client.frame_filter = flip
        try:
            return self._send(op, timeout_s, kwargs)
        finally:
            self.client.frame_filter = None

    def _partitioned_call(self, f: Fault, op: str,
                          timeout_s: Optional[float],
                          kwargs: dict) -> dict:
        self._set_partitioned(True)
        if int(f.arg2) == 0:
            # two-way: the frame never leaves this host — definitely
            # not executed, the connection looks dead
            self.client.close()
            raise _rpc().RpcDown(f"{op}: partitioned (chaos)")
        # one-way: the request crosses, the response is lost — the
        # worker EXECUTED this call and the caller cannot know. The
        # swallowed response still reaches the observer (accounting).
        try:
            resp = self._send(op, timeout_s, kwargs)
        except _rpc().RpcError:
            pass
        else:
            self._observe_response(resp)
        self.client.close()
        raise _rpc().RpcTimeout(f"{op}: response lost to one-way "
                                f"partition (chaos)")

    # ---------------------------------------------------------- plumbing

    def _observe_response(self, resp: dict) -> None:
        cb = getattr(self.observer, "net_chaos_response", None)
        if cb is not None:
            cb(resp)

    def _set_partitioned(self, now_active: bool) -> None:
        if self.partitioned == now_active:
            return
        self.partitioned = now_active
        cb = getattr(self.observer, "net_chaos_partition", None)
        if cb is not None:
            cb(now_active)
