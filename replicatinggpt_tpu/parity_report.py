"""Runnable parity report: JAX backend vs the retained PyTorch-CPU path.

SURVEY.md §7 build-plan item 7 names "parity report vs the retained PyTorch
scripts" as a deliverable; BASELINE.json keeps the torch path as the CPU
reference. This tool produces that report as markdown:

1. forward parity — same injected weights, same inputs, both GPT-1 and
   GPT-2 flavors (untied/relu, tied/gelu): max |logits diff|, loss diff;
2. gradient parity — max relative grad diff over the whole tree;
3. training-curve parity — N AdamW steps on the same seeded batch stream
   through both backends (optax.adamw vs torch.optim.AdamW, decoupled
   weight decay both sides): per-step loss deltas and final spread;
4. the documented semantic deviations (SURVEY.md §8 fidelity decisions).

Run: python -m replicatinggpt_tpu.parity_report [--out PARITY_REPORT.md]
(CPU-forced; ~2 min.)
"""

from __future__ import annotations

import argparse
import dataclasses
import io
import sys


def _forward_and_grad_parity(report: io.StringIO) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import torch

    from .config import ModelConfig
    from .models.gpt import forward, init_params
    from .reference_torch import RefGPT, params_to_torch

    from .reference_torch import torch_to_params

    report.write("## 1-2. Forward / gradient parity (same weights, same "
                 "inputs)\n\n")
    report.write("| flavor | max |logits diff| | loss diff | max rel grad "
                 "diff |\n|---|---|---|---|\n")
    # inputs are flavor-independent: build them once, ONE host pull,
    # before the comparison loop
    x = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                      65), np.int64)
    y = np.asarray(jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0,
                                      65), np.int64)
    labels = []
    deltas = []          # per-flavor (dl, dloss), accumulated ON DEVICE
    grad_pairs = []      # per-flavor (jax grad tree, torch grad tree)
    for tied, act, label in ((False, "relu", "GPT-1 (untied, ReLU)"),
                             (True, "gelu", "GPT-2 (tied, GELU)")):
        cfg = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                          n_embd=32, dropout=0.0, attn_dropout=0.0,
                          tied_head=tied, activation=act, dtype="float32")
        params = init_params(jax.random.PRNGKey(0), cfg)

        jlogits, jloss = forward(params, jnp.asarray(x, jnp.int32), cfg,
                                 targets=jnp.asarray(y, jnp.int32))

        tm = params_to_torch(params, RefGPT(cfg))
        tlogits, tloss = tm(torch.from_numpy(x), torch.from_numpy(y))

        # torch -> numpy is a host-side detach, not a device sync; the
        # deltas against it stay jax scalars until the fetch after the loop
        dl = jnp.abs(jlogits - tlogits.detach().numpy()).max()
        dloss = jnp.abs(jloss - tloss.detach().numpy())

        # gradients
        def jf(p):
            _, l = forward(p, jnp.asarray(x, jnp.int32), cfg,
                           targets=jnp.asarray(y, jnp.int32))
            return l
        jg = jax.grad(jf)(params)
        tm.zero_grad()
        tloss.backward()
        # reuse the name mapping by reading grads through a weight-shaped
        # copy: swap .data with .grad, convert, swap back
        for p in tm.parameters():
            p.data, p.grad = p.grad, p.data
        tg = torch_to_params(tm)
        for p in tm.parameters():
            p.data, p.grad = p.grad, p.data

        labels.append(label)
        deltas.append(jnp.stack([dl, dloss]))
        grad_pairs.append((jg, tg))
    # TWO device boundary crossings for the whole report, both after the
    # loop: the stacked logit/loss deltas and the gradient trees. The
    # rel-grad reduction runs on host in float64 (it compares values
    # near f32 epsilon — doing it in f32 would measure rounding noise).
    vals = np.asarray(jnp.stack(deltas))
    host_jgs = jax.device_get([jg for jg, _ in grad_pairs])
    rels = []
    for host_jg, (_, tg) in zip(host_jgs, grad_pairs):
        rel = np.float64(0.0)
        for ja, ta in zip(jax.tree_util.tree_leaves(host_jg),
                          jax.tree_util.tree_leaves(tg)):
            ja64, ta64 = ja.astype(np.float64), ta.astype(np.float64)
            denom = np.maximum(np.abs(ta64), 1e-6)
            rel = np.maximum(rel, (np.abs(ja64 - ta64) / denom).max())
        rels.append(rel)
    for label, (dl, dloss), rel in zip(labels, vals, rels):
        report.write(f"| {label} | {dl:.2e} | {dloss:.2e} | {rel:.2e} |\n")
    report.write("\n")


def _training_curve_parity(report: io.StringIO, steps: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import torch

    from .config import get_config
    from .data.dataset import TokenDataset, load_corpus
    from .data.loader import RandomBatcher
    from .models.gpt import init_params
    from .reference_torch import RefGPT, params_to_torch
    from .train.steps import make_train_step
    from .tokenizers import get_tokenizer

    cfg = get_config("test-tiny")
    mcfg = dataclasses.replace(cfg.model, dropout=0.0, attn_dropout=0.0)
    tcfg = cfg.train
    text = load_corpus(cfg.dataset)
    tok = get_tokenizer("char", corpus_text=text)
    ds = TokenDataset.from_text(text, tok, tcfg.val_fraction)

    # identical batch stream for both backends; the torch copy is
    # converted to int64 up front (host numpy -> host numpy, no device
    # involved) so the training loops below do zero per-step conversions
    stream = list(RandomBatcher(ds.train, 8, mcfg.block_size, seed=7)
                  .next_batch() for _ in range(steps))
    stream64 = [(np.asarray(xb, np.int64), np.asarray(yb, np.int64))
                for xb, yb in stream]

    # one init, transferred losslessly to torch — the curves start from
    # bit-identical weights
    from .train.state import TrainState, make_optimizer
    params0 = init_params(jax.random.PRNGKey(0), mcfg)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params0,
                       opt_state=make_optimizer(tcfg).init(params0),
                       rng=jax.random.PRNGKey(1))
    step = make_train_step(mcfg, tcfg, donate=False)
    jdev = []
    for xb, yb in stream:
        state, metrics = step(state, (jnp.asarray(xb), jnp.asarray(yb)))
        jdev.append(metrics["loss"])          # stays on device
    # the whole jax loss curve crosses the device boundary ONCE
    jl = [float(v) for v in np.asarray(jnp.stack(jdev))]

    tm = params_to_torch(params0, RefGPT(mcfg))
    opt = torch.optim.AdamW(tm.parameters(), lr=tcfg.lr,
                            betas=tcfg.betas, eps=1e-8,
                            weight_decay=tcfg.weight_decay)
    tdev = []
    for xb, yb in stream64:
        opt.zero_grad(set_to_none=True)
        _, loss = tm(torch.from_numpy(xb), torch.from_numpy(yb))
        loss.backward()
        opt.step()
        tdev.append(loss.detach())            # torch host scalar
    tl = [float(v) for v in tdev]

    diffs = [abs(a - b) for a, b in zip(jl, tl)]
    report.write(f"## 3. Training-curve parity ({steps} AdamW steps, "
                 "same init, same batches, dropout off)\n\n")
    report.write("| step | jax loss | torch loss | diff |\n|---|---|---|---|\n")
    for i in (0, 1, steps // 2, steps - 1):
        report.write(f"| {i} | {jl[i]:.6f} | {tl[i]:.6f} | "
                     f"{diffs[i]:.2e} |\n")
    report.write(f"\nmax per-step |diff| over the run: "
                 f"{max(diffs):.2e}; final spread {diffs[-1]:.2e} "
                 f"(float32 accumulation-order noise only).\n\n")


DEVIATIONS = """## 4. Documented semantic deviations (SURVEY.md §8 policy)

Replicated: loss-line formats, eval cadence/semantics, sampling
disciplines, HF import mapping, seeds/batch disciplines. Fixed, not
replicated (reference as committed crashes or diverges):

- B1/B5 vocab-tokenizer mismatches -> vocab always covers the tokenizer.
- B2 broken nltk branch -> dropped.
- B3 undefined `decode` on the tiktoken path -> decode on every tokenizer.
- B4 lr=0.5 literal -> the declared 2e-4 is actually used.
- B6 dead sampling code -> alive (`sample/generate.py`, top-k 50 preset).
- Q1 attention scaled by n_embd -> head_dim scaling.
- Q2 declared-but-unapplied dropouts -> applied.
- Q4 NANOGPT_SCALE_INIT tag ignored -> residual init std/sqrt(2L) real.
- generate() beyond block_size: per-token window crop (uncacheable) ->
  half-window refresh (KV-cache compatible; documented in sample/).
- B1's default tokenizer branch (o200k_base under a hard-coded 50257
  vocab) -> preset `o200k-shakespeare`: vocab 200,064 covers the real
  id space, chunked CE head keeps the giant-vocab logits off HBM.
- The reference's model.pth epilogue (GPT1.py:239-241) -> the
  `export-torch` subcommand writes the same torch state_dict artifact
  from any framework checkpoint (round-trips through RefGPT).
"""


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="PARITY_REPORT.md")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--platform", default="cpu")
    args = p.parse_args(argv)

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    report = io.StringIO()
    report.write("# PARITY REPORT — JAX/TPU backend vs PyTorch-CPU "
                 "reference path\n\nGenerated by "
                 "`python -m replicatinggpt_tpu.parity_report`. The torch "
                 "side is `reference_torch.py` (the retained CPU reference "
                 "named in BASELINE.json), weight-transferred losslessly "
                 "from the same JAX init.\n\n")
    _forward_and_grad_parity(report)
    _training_curve_parity(report, args.steps)
    report.write(DEVIATIONS)

    text = report.getvalue()
    with open(args.out, "w") as f:
        f.write(text)
    print(text)
    print(f"written to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
