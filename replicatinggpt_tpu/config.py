"""Configuration system for the TPU-native GPT framework.

Unifies the reference's three scattered config surfaces (module-level globals
in GPT1.py:12-23 and GPT-2.py:6-16, plus the GPTConfig dataclass at
GPT-2.py:81-87) into frozen dataclasses with named presets covering every
configuration the reference can express, and the five BASELINE.json workloads.

Everything is hashable/frozen so configs can be closed over by ``jax.jit`` as
static arguments without retracing surprises.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass(frozen=True)
class ModelConfig:
    """Architecture of a decoder-only pre-LN transformer LM.

    One definition serves both reference flavors (GPT1.py:100-212 and
    GPT-2.py:22-128); they differ only in field values:

    - GPT-1 flavor: untied lm_head (GPT1.py:174), ReLU MLP (GPT1.py:144),
      dropout 0.2.
    - GPT-2 flavor: tied wte/lm_head (GPT-2.py:104), GELU MLP (GPT-2.py:62),
      fused QKV (always fused here; the per-head Python loop of GPT1.py:130
      is a strictly worse formulation on any hardware).
    """

    vocab_size: int = 65
    block_size: int = 256
    n_layer: int = 6
    n_head: int = 6
    n_embd: int = 384
    dropout: float = 0.2          # residual + MLP dropout (GPT1.py:147)
    attn_dropout: float = 0.2     # dropout on attention weights (GPT1.py:117)
    tied_head: bool = True        # GPT-2.py:104 weight tying; False = GPT1.py:174
    activation: str = "gelu"      # 'gelu' (GPT-2.py:62) or 'relu' (GPT1.py:144)
    layernorm_eps: float = 1e-5
    init_std: float = 0.02        # GPT-2 paper init; reference's NANOGPT_SCALE_INIT
                                  # tag (GPT-2.py:31,59) is honored here for real:
                                  # residual projections get std/sqrt(2*n_layer)
    # --- numerics -----------------------------------------------------------
    dtype: str = "bfloat16"       # activation/compute dtype on TPU (MXU-native)
    param_dtype: str = "float32"  # master params stay f32
    # --- execution ----------------------------------------------------------
    attention_impl: str = "auto"  # 'auto' | 'einsum' | 'flash' | 'ring' |
                                  # 'ulysses' (seq-parallel all-to-all)
    remat: bool = False           # jax.checkpoint each block (HBM <-> FLOPs)
    remat_policy: str = "full"    # 'full' (save nothing) | 'dots' (save
                                  # matmul outputs, recompute elementwise:
                                  # jax.checkpoint_policies.dots_saveable) |
                                  # 'dots_no_batch' (…with_no_batch_dims…).
                                  # Measured at 350M B=8 on v5e-16G: 'full'
                                  # wins — see benchmarks/RESULTS.md
                                  # selective-remat table
    loss_chunk: int = 0
    # Rows of the flattened (B*T, V) logits computed per lax.scan step in
    # the training loss head; 0 = the plain one-shot head. Non-zero never
    # materializes the full f32 logits array (models.gpt._chunked_ce_loss)
    # — at GPT-2 vocab that array is the step's largest HBM tenant. With
    # loss_chunk on, forward(targets=...) returns (None, loss): callers
    # that need logits keep the default. Opt-in until the hardware A/B
    # (tools/hw_validate.py ce_chunk_off/ce_chunk_on) sizes the win.
    decode_cache_layout: str = "heads"
    # KV-cache memory layout for decode: 'heads' = (L, B, H, S, D) (the
    # original layout), 'packed' = (L, B, S, C) with heads as static lane
    # slices of the C row. At D=64 the TPU tiles a (S, D)-minor array to
    # 128 lanes, so the heads layout physically streams ~2x the logical
    # cache bytes per decode step — the packed layout stores fully-packed
    # (S, C) rows and reads them through ops/decode_pallas.py's
    # packed_decode_attention kernel (the packed-flash lane-slice trick
    # applied to decode). 'heads' stays the default until the layout A/B
    # validates on hardware (tools/hw_validate.py decode_sweep_packed).
    act_quant: str = "none"
    # W8A8 serving: 'int8' quantizes the ACTIVATION rows feeding the
    # already-int8-quantized weight matmuls of the cached decode paths
    # (per-row symmetric, models.gpt._wmm) so the contraction runs
    # int8 x int8 -> int32. No effect unless the params carry int8
    # kernels (quant/weights.py) — the serve engine sets this from
    # EngineConfig.act_quant; training paths never quantize. 'none'
    # default keeps every existing config byte-identical.
    scan_layers: Optional[bool] = None
    # lax.scan over stacked layer params. None = auto: on TPU, unroll
    # shallow stacks (n_layer <= 16) — measured on v5e, unrolling the
    # 6-layer char-GPT cuts step time 25.9 -> 19.7 ms (+31% throughput)
    # because scan blocks XLA's cross-layer fusion/overlap; scan deep
    # stacks, where compile time and code size dominate. On CPU scan
    # always (unrolling measured strictly worse there: +60% compile AND
    # +28% step time). Params stay stacked (L, ...) either way, so
    # shardings/checkpoints are unaffected.

    @property
    def use_layer_scan(self) -> bool:
        if self.scan_layers is not None:
            return self.scan_layers
        if self.n_layer > 16:
            return True
        import jax
        return jax.default_backend() != "tpu"

    @property
    def head_dim(self) -> int:
        assert self.n_embd % self.n_head == 0, (
            f"n_embd={self.n_embd} not divisible by n_head={self.n_head}"
        )
        return self.n_embd // self.n_head

    def validate(self) -> "ModelConfig":
        _ = self.head_dim
        assert self.activation in ("gelu", "relu"), self.activation
        assert self.attention_impl in ("auto", "einsum", "flash", "ring",
                                       "ulysses")
        assert self.remat_policy in ("full", "dots", "dots_no_batch"), (
            self.remat_policy)
        assert self.act_quant in ("none", "int8"), self.act_quant
        return self


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh. Axis names are fixed framework-wide.

    - ``data``: data parallelism (batch dim) + FSDP parameter sharding
    - ``seq``:  sequence/context parallelism (ring attention over ICI)
    - ``model``: tensor parallelism (column/row-parallel matmuls)
    - ``pipe``: pipeline parallelism (layer-stacked block params sharded by
      stage; microbatches flow via ppermute — parallel/pipeline.py)

    The reference has no distributed machinery (SURVEY.md §2.1-§2.2); this is
    the TPU-native replacement: XLA GSPMD collectives derived from
    NamedSharding annotations over this mesh.
    """

    data: int = 1
    seq: int = 1
    model: int = 1
    pipe: int = 1
    fsdp: bool = False  # additionally shard params/opt-state over 'data'
    microbatches: int = 0  # pipeline microbatches (0 = 2 per stage)

    @property
    def n_devices(self) -> int:
        return self.data * self.seq * self.model * self.pipe

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return ("data", "seq", "model", "pipe")


@dataclass(frozen=True)
class TrainConfig:
    """Optimization + loop schedule.

    Reference semantics preserved: AdamW (GPT1.py:218), periodic mean-of-K
    train/val eval (GPT1.py:85-98, eval_interval GPT1.py:223), per-step loss
    logging (GPT-2.py:229). The committed lr=5e-1 bug (GPT1.py:218) is fixed
    to the declared 2e-4 (GPT1.py:17) per SURVEY.md §8-B4.
    """

    batch_size: int = 64
    lr: float = 2e-4
    betas: Tuple[float, float] = (0.9, 0.999)
    weight_decay: float = 0.01
    grad_clip: float = 0.0           # 0 = off (reference has none)
    max_iters: int = 3000
    warmup_iters: int = 0
    lr_schedule: str = "constant"    # 'constant' | 'cosine'
    min_lr: float = 0.0
    eval_interval: int = 200
    eval_iters: int = 200
    log_interval: int = 10
    steps_per_dispatch: int = 1      # >1: lax.scan K optimizer steps per
                                     # dispatch (amortizes host->device
                                     # round trips; loss curve unchanged)
    grad_accum_steps: int = 1        # >1: each optimizer step averages
                                     # grads over this many batch_size
                                     # microbatches (effective batch =
                                     # grad_accum_steps * batch_size) via an
                                     # on-device lax.scan — big global
                                     # batches without the activation memory
    seed: int = 1337                 # GPT1.py:10
    sampling: str = "random"         # 'random' (GPT1.py:75-83) |
                                     # 'sequential' (GPT-2.py:200-213)
    val_fraction: float = 0.1        # 90/10 split, GPT1.py:68-70
    checkpoint_every: int = 0        # 0 = only at end
    checkpoint_dir: str = "checkpoints"


@dataclass(frozen=True)
class Config:
    model: ModelConfig = ModelConfig()
    train: TrainConfig = TrainConfig()
    mesh: MeshConfig = MeshConfig()
    tokenizer: str = "char"          # 'char' | 'bpe' | 'bpe:<path>' |
                                     # 'tiktoken:gpt2' | 'tiktoken:o200k_base'
    dataset: str = "datasets/shakespeare.txt"
    name: str = "default"

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)


# ---------------------------------------------------------------------------
# Presets: every configuration the reference can express + BASELINE workloads
# ---------------------------------------------------------------------------

def _gpt2_ladder(n_layer: int, n_head: int, n_embd: int,
                 remat: bool = False) -> ModelConfig:
    # Size table from GPT-2.py:140-147 (vocab 50257, context 1024).
    # remat=True for 350M+: without it the layer-stacked residuals of a
    # 24-48 layer scan (~18 GB at 350M/B=8) exceed a single chip's HBM —
    # measured OOM on v5e-16G; with remat the same config trains (the
    # FLOPs-for-HBM trade jax.checkpoint exists for).
    return ModelConfig(
        vocab_size=50257, block_size=1024, n_layer=n_layer, n_head=n_head,
        n_embd=n_embd, dropout=0.0, attn_dropout=0.0, tied_head=True,
        activation="gelu", remat=remat,
    )


PRESETS = {
    # BASELINE.json config 1/2: canonical char-GPT (n_embd=384 per
    # BASELINE.md; GPT1.py semantics: untied head, ReLU, dropout 0.2).
    "char-gpt": Config(
        name="char-gpt",
        model=ModelConfig(
            vocab_size=65, block_size=256, n_layer=6, n_head=6, n_embd=384,
            dropout=0.2, attn_dropout=0.2, tied_head=False, activation="relu",
        ),
        train=TrainConfig(batch_size=64, lr=2e-4, max_iters=3000,
                          eval_interval=200, eval_iters=200, seed=1337,
                          sampling="random"),
        tokenizer="char",
    ),
    # The GPT1.py file exactly as committed (n_embd=126), for parity audits.
    "char-gpt1-ref": Config(
        name="char-gpt1-ref",
        model=ModelConfig(
            vocab_size=65, block_size=256, n_layer=6, n_head=6, n_embd=126,
            dropout=0.2, attn_dropout=0.2, tied_head=False, activation="relu",
        ),
        train=TrainConfig(batch_size=64, lr=2e-4, max_iters=3000,
                          eval_interval=200, eval_iters=200, seed=1337,
                          sampling="random"),
        tokenizer="char",
    ),
    # The GPT-2.py training run as intended (B=4/T=32/50 iters,
    # lr 3e-4, sequential loader; vocab fixed to the tokenizer's per §8-B5).
    "gpt2-shakespeare": Config(
        name="gpt2-shakespeare",
        model=ModelConfig(
            vocab_size=50304, block_size=256, n_layer=6, n_head=6, n_embd=384,
            dropout=0.0, attn_dropout=0.0, tied_head=True, activation="gelu",
        ),
        train=TrainConfig(batch_size=4, lr=3e-4, max_iters=50,
                          eval_interval=0, eval_iters=20, seed=1337,
                          sampling="sequential", log_interval=1),
        tokenizer="bpe",
    ),
    # BASELINE.json config 3: GPT-2 124M, 8-chip DP.
    "gpt2-small": Config(
        name="gpt2-small",
        model=_gpt2_ladder(12, 12, 768),
        train=TrainConfig(batch_size=32, lr=3e-4, max_iters=1000,
                          sampling="sequential", lr_schedule="cosine",
                          warmup_iters=100, grad_clip=1.0),
        mesh=MeshConfig(data=8),
        tokenizer="bpe",
    ),
    # BASELINE.json config 4: GPT-2 350M, v4-32, bf16, FSDP.
    "gpt2-medium": Config(
        name="gpt2-medium",
        model=_gpt2_ladder(24, 16, 1024, remat=True),
        train=TrainConfig(batch_size=64, lr=3e-4, max_iters=1000,
                          sampling="sequential", lr_schedule="cosine",
                          warmup_iters=100, grad_clip=1.0),
        mesh=MeshConfig(data=16, fsdp=True),
        tokenizer="bpe",
    ),
    "gpt2-large": Config(
        name="gpt2-large", model=_gpt2_ladder(36, 20, 1280, remat=True),
        mesh=MeshConfig(data=16, fsdp=True), tokenizer="bpe",
    ),
    "gpt2-xl": Config(
        name="gpt2-xl", model=_gpt2_ladder(48, 25, 1600, remat=True),
        mesh=MeshConfig(data=16, fsdp=True), tokenizer="bpe",
    ),
    # The reference GPT1.py's DEFAULT tokenizer branch as intended:
    # tiktoken o200k_base with the §8-B1 vocab bug fixed (the reference
    # hard-coded vocab 50257 under a ~200k-token encoding, so most ids
    # indexed past the embedding; here the tokenizer's true n_vocab
    # (200,019) is rounded up to an MXU-friendly 200,064 = 128*1563).
    # Giant-vocab caveat measured on v5e (benchmarks/RESULTS.md o200k
    # row): the (B*T, C) @ (C, 200k) f32 logits matmul + softmax
    # dominates the step at char-GPT scale. Needs tiktoken's cached BPE
    # ranks (network once); this zero-egress image measures the
    # giant-vocab cost via `--preset char-gpt --vocab-size 200064`.
    "o200k-shakespeare": Config(
        name="o200k-shakespeare",
        model=ModelConfig(
            vocab_size=200_064, block_size=256, n_layer=6, n_head=6,
            n_embd=384, dropout=0.2, attn_dropout=0.2, tied_head=False,
            activation="relu",
            # at V=200k the one-shot f32 logits array is B*T*V*4 =
            # 13.1 GB — past a 16 GB chip once the backward doubles it;
            # the chunked CE head makes this preset feasible at all
            # (2048 divides B*T = 16384)
            loss_chunk=2048,
        ),
        train=TrainConfig(batch_size=64, lr=2e-4, max_iters=3000,
                          eval_interval=200, eval_iters=200, seed=1337,
                          sampling="random"),
        tokenizer="tiktoken:o200k_base",
    ),
    # Tiny config for tests / smoke runs.
    "test-tiny": Config(
        name="test-tiny",
        model=ModelConfig(
            vocab_size=65, block_size=32, n_layer=2, n_head=2, n_embd=32,
            dropout=0.0, attn_dropout=0.0, tied_head=True, activation="gelu",
            dtype="float32",
        ),
        train=TrainConfig(batch_size=8, lr=1e-3, max_iters=50,
                          eval_interval=25, eval_iters=4, log_interval=10),
        tokenizer="char",
    ),
}


def get_config(name: str, **overrides) -> Config:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    cfg = PRESETS[name]
    return cfg.replace(**overrides) if overrides else cfg


# ---------------------------------------------------------------------------
# CLI overlay (the reference has no CLI at all — SURVEY.md §5 config row)
# ---------------------------------------------------------------------------

def add_config_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--preset", default="char-gpt", choices=sorted(PRESETS))
    p.add_argument("--backend", default="jax", choices=["jax"],
                   help="execution backend (BASELINE.json names --backend=jax)")
    # model overrides — each registered under BOTH spellings
    # (--vocab_size and --vocab-size): the o200k preset's documented
    # repro command uses the dashed form, and every other flag here is
    # dashed, so the underscore-only registration was a paper cut
    # (ADVICE round 5)
    for f in ("vocab_size", "block_size", "n_layer", "n_head", "n_embd"):
        p.add_argument(f"--{f}", f"--{f.replace('_', '-')}", type=int,
                       default=None)
    p.add_argument("--dropout", type=float, default=None)
    p.add_argument("--dtype", type=str, default=None)
    p.add_argument("--attention", dest="attention_impl", default=None,
                   choices=["auto", "einsum", "flash", "ring", "ulysses"])
    p.add_argument("--remat", action="store_true", default=None,
                   help="jax.checkpoint each block (trade FLOPs for HBM)")
    p.add_argument("--no-remat", dest="remat", action="store_false",
                   help="disable the preset's remat (e.g. 350M+ presets "
                        "default remat on for single-chip HBM; a pod-slice "
                        "FSDP run may not need it)")
    p.add_argument("--loss-chunk", dest="loss_chunk", type=int, default=None,
                   help="chunked training CE head: rows per scan step "
                        "(0 = one-shot logits; see ModelConfig.loss_chunk)")
    p.add_argument("--decode-cache-layout", dest="decode_cache_layout",
                   default=None, choices=["heads", "packed"],
                   help="KV-cache memory layout for decode (see "
                        "ModelConfig.decode_cache_layout)")
    p.add_argument("--remat-policy", dest="remat_policy", default=None,
                   choices=["full", "dots", "dots_no_batch"],
                   help="what jax.checkpoint saves per block: 'full' "
                        "recomputes everything (v5e-measured default), "
                        "'dots'/'dots_no_batch' save matmul outputs")
    # train overrides
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--max-iters", type=int, default=None)
    p.add_argument("--eval-interval", type=int, default=None)
    p.add_argument("--eval-iters", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--steps-per-dispatch", type=int, default=None,
                   help="lax.scan K optimizer steps per device dispatch")
    p.add_argument("--grad-accum-steps", type=int, default=None,
                   help="microbatches averaged per optimizer step "
                        "(effective batch = this * batch-size)")
    # mesh overrides
    p.add_argument("--dp", type=int, default=None, help="mesh data axis size")
    p.add_argument("--sp", type=int, default=None, help="mesh seq axis size")
    p.add_argument("--tp", type=int, default=None, help="mesh model axis size")
    p.add_argument("--pp", type=int, default=None, help="mesh pipe axis size")
    p.add_argument("--microbatches", type=int, default=None,
                   help="pipeline microbatches (default 2 per stage)")
    p.add_argument("--fsdp", action="store_true", default=None)
    p.add_argument("--lr-schedule", default=None,
                   choices=["constant", "cosine"])
    p.add_argument("--warmup-iters", type=int, default=None)
    p.add_argument("--min-lr", type=float, default=None)
    p.add_argument("--grad-clip", type=float, default=None)
    p.add_argument("--log-interval", type=int, default=None)
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--dataset", default=None)


#: (dest, flag) pairs for every MODEL-shape override registered by
#: add_config_flags — kept adjacent so a new model flag is added to
#: both in one edit. config_override_args() reconstructs these for a
#: respawned process (`serve --multiproc` workers): a flag missing
#: here means a worker silently builds a DIFFERENT model than the
#: operator asked for.
MODEL_OVERRIDE_FLAGS = (
    ("vocab_size", "--vocab-size"), ("block_size", "--block-size"),
    ("n_layer", "--n-layer"), ("n_head", "--n-head"),
    ("n_embd", "--n-embd"), ("dropout", "--dropout"),
    ("dtype", "--dtype"), ("attention_impl", "--attention"),
    ("loss_chunk", "--loss-chunk"),
    ("decode_cache_layout", "--decode-cache-layout"),
    ("remat_policy", "--remat-policy"),
)


def config_override_args(args: argparse.Namespace) -> list:
    """Reconstruct the model-override CLI arguments present on
    ``args`` (None = unset = omitted) so one process can spawn another
    with the same model config through its own add_config_flags
    parser."""
    out: list = []
    for dest, flag in MODEL_OVERRIDE_FLAGS:
        v = getattr(args, dest, None)
        if v is not None:
            out += [flag, str(v)]
    remat = getattr(args, "remat", None)
    if remat is not None:                # tri-state store_true/false
        out.append("--remat" if remat else "--no-remat")
    return out


def config_from_args(args: argparse.Namespace) -> Config:
    cfg = get_config(args.preset)
    m, t, mesh = cfg.model, cfg.train, cfg.mesh
    mk = {k: v for k, v in (
        ("vocab_size", args.vocab_size), ("block_size", args.block_size),
        ("n_layer", args.n_layer), ("n_head", args.n_head),
        ("n_embd", args.n_embd), ("dropout", args.dropout),
        ("dtype", args.dtype), ("attention_impl", args.attention_impl),
        ("remat", args.remat), ("remat_policy", args.remat_policy),
        ("decode_cache_layout", getattr(args, "decode_cache_layout", None)),
        ("loss_chunk", getattr(args, "loss_chunk", None)),
    ) if v is not None}
    if args.dropout is not None:
        mk["attn_dropout"] = args.dropout
    tk = {k: v for k, v in (
        ("batch_size", args.batch_size), ("lr", args.lr),
        ("max_iters", args.max_iters), ("eval_interval", args.eval_interval),
        ("eval_iters", args.eval_iters), ("seed", args.seed),
        ("steps_per_dispatch", args.steps_per_dispatch),
        ("grad_accum_steps", args.grad_accum_steps),
        ("lr_schedule", args.lr_schedule),
        ("warmup_iters", args.warmup_iters), ("min_lr", args.min_lr),
        ("grad_clip", args.grad_clip), ("log_interval", args.log_interval),
    ) if v is not None}
    meshk = {k: v for k, v in (
        ("data", args.dp), ("seq", args.sp), ("model", args.tp),
        ("pipe", args.pp), ("microbatches", args.microbatches),
        ("fsdp", args.fsdp),
    ) if v is not None}
    ck = {}
    if args.tokenizer is not None:
        ck["tokenizer"] = args.tokenizer
    if args.dataset is not None:
        ck["dataset"] = args.dataset
    return cfg.replace(
        model=dataclasses.replace(m, **mk).validate(),
        train=dataclasses.replace(t, **tk),
        mesh=dataclasses.replace(mesh, **meshk),
        **ck,
    )
