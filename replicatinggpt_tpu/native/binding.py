"""ctypes binding for the native fastpath (fastpath.cpp).

Public surface (all take/return NumPy arrays; every function has identical
NumPy-fallback semantics when the library is unavailable):

- ``available()`` — did the .so build/load?
- ``encode_lut(data_bytes, lut)`` — byte->id map; raises on unmapped bytes.
- ``gather_batch(data, offsets, T)`` — fused (B,T) x/y window gather.
- ``bpe_encode_words(word_units, word_off, merge_table)`` — greedy
  lowest-rank merges over pre-split words, in token-id space.

Environment toggle: ``RGTPU_NO_NATIVE=1`` disables the native path (used by
the parity tests to exercise both sides).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Tuple

import numpy as np

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("RGTPU_NO_NATIVE"):
            return None
        from .build import build
        path = build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.rg_encode_lut.restype = ctypes.c_long
        lib.rg_encode_lut.argtypes = [_u8p, ctypes.c_long, _i32p, _i32p]
        lib.rg_gather_batch.restype = None
        lib.rg_gather_batch.argtypes = [_i32p, ctypes.c_long, _i64p,
                                        ctypes.c_int, ctypes.c_int,
                                        _i32p, _i32p]
        lib.rg_bpe_encode.restype = ctypes.c_long
        lib.rg_bpe_encode.argtypes = [_i32p, _i64p, ctypes.c_long,
                                      _i32p, _i32p, _i32p, ctypes.c_long,
                                      ctypes.c_int64, _i32p]
        lib.rg_bpe_free_table.restype = None
        lib.rg_bpe_free_table.argtypes = [ctypes.c_int64]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def encode_lut(data: bytes, lut: np.ndarray) -> np.ndarray:
    """Map each byte of ``data`` through ``lut`` (int32[256], -1=unmapped).

    Raises ValueError if any byte is unmapped (mirrors dict KeyError on the
    Python path)."""
    buf = np.frombuffer(data, np.uint8)
    lut = np.ascontiguousarray(lut, np.int32)
    lib = _load()
    if lib is not None:
        out = np.empty(len(buf), np.int32)
        bad = lib.rg_encode_lut(buf, len(buf), lut, out)
        if bad:
            raise ValueError(f"{bad} bytes outside the tokenizer alphabet")
        return out
    ids = lut[buf]
    if (ids < 0).any():
        raise ValueError(
            f"{int((ids < 0).sum())} bytes outside the tokenizer alphabet")
    return ids


def gather_batch(data: np.ndarray, offsets: np.ndarray,
                 T: int) -> Tuple[np.ndarray, np.ndarray]:
    """x[b] = data[o_b : o_b+T], y[b] = data[o_b+1 : o_b+T+1]."""
    data = np.ascontiguousarray(data, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    B = len(offsets)
    # hard bounds check, not assert: under python -O an assert is stripped
    # and the native path would memcpy past the end of the data buffer
    if B and (offsets.min() < 0 or offsets.max() + T + 1 > len(data)):
        raise ValueError(
            f"offsets out of range: window [{int(offsets.min())}, "
            f"{int(offsets.max()) + T + 1}) exceeds data of {len(data)}")
    lib = _load()
    if lib is not None:
        x = np.empty((B, T), np.int32)
        y = np.empty((B, T), np.int32)
        lib.rg_gather_batch(data, len(data), offsets, B, T, x, y)
        return x, y
    idx = offsets[:, None] + np.arange(T + 1)[None, :]
    win = data[idx]
    return np.ascontiguousarray(win[:, :-1]), np.ascontiguousarray(win[:, 1:])


import itertools

_table_ids = itertools.count(1)  # process-unique C++ cache tokens


class BpeMergeTable:
    """Rank-ordered merge rules in token-id space, held in stable arrays.

    Each instance mints a process-unique ``table_id``; the C++ side caches
    its hash map under that token (fastpath.cpp MergeCache) — never under
    an array pointer, which the allocator can recycle across tokenizer
    lifetimes. One instance per tokenizer amortizes the table build across
    encode calls; the cache entry is freed when the instance is collected.
    Pairs must be pre-deduplicated by the caller (Python-dict semantics:
    for a duplicate (left,right) pair the last rank wins —
    tokenizers.py:111).
    """

    def __init__(self, pair_keys: np.ndarray, ranks: np.ndarray,
                 new_ids: np.ndarray):
        pair_keys = np.asarray(pair_keys, np.int32).reshape(-1, 2)
        ranks = np.asarray(ranks, np.int32)
        order = np.argsort(ranks, kind="stable")  # row index == priority
        self.left = np.ascontiguousarray(pair_keys[order, 0], np.int32)
        self.right = np.ascontiguousarray(pair_keys[order, 1], np.int32)
        self.new_ids = np.ascontiguousarray(
            np.asarray(new_ids, np.int32)[order], np.int32)
        self.table_id = next(_table_ids)

    def __del__(self):
        lib = _lib  # only free if the library was ever loaded
        if lib is not None:
            try:
                lib.rg_bpe_free_table(self.table_id)
            except Exception:
                pass  # interpreter teardown


def bpe_encode_words(word_units: np.ndarray, word_off: np.ndarray,
                     table: BpeMergeTable) -> Optional[np.ndarray]:
    """Greedy BPE merge loop over a flattened batch of words.

    word_units: concatenated byte-ids of all words; word_off: int64[W+1]
    offsets. Returns merged ids, or None when the native library is
    unavailable (the caller keeps its Python loop as the fallback — it
    needs the string domain anyway for cache warm-up).
    """
    lib = _load()
    if lib is None:
        return None
    word_units = np.ascontiguousarray(word_units, np.int32)
    word_off = np.ascontiguousarray(word_off, np.int64)
    out = np.empty(len(word_units), np.int32)
    n = lib.rg_bpe_encode(word_units, word_off, len(word_off) - 1,
                          table.left, table.right, table.new_ids,
                          len(table.left), table.table_id, out)
    return out[:n]
