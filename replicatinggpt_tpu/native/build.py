"""On-demand compilation of the native fastpath library.

No pybind11 in this environment, so the binding is plain ctypes over an
``extern "C"`` ABI; the library is compiled once per source change with g++
and cached next to the source (``_build/fastpath-<hash>.so``). Everything
degrades gracefully: if no compiler is available the Python/NumPy fallbacks
run instead (binding.py), so the framework never hard-depends on a
toolchain at runtime.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Optional

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "fastpath.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_build")


def _src_tag() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def lib_path() -> str:
    return os.path.join(_BUILD_DIR, f"fastpath-{_src_tag()}.so")


def build(verbose: bool = False) -> Optional[str]:
    """Compile (if stale) and return the .so path, or None on failure."""
    out = lib_path()
    if os.path.exists(out):
        return out
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # compile into a temp file then atomic-rename, so concurrent builders
    # (e.g. pytest-xdist workers) never load a half-written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    # portable codegen only: the cached .so can outlive the build host (the
    # Dockerfile pre-builds it into the image), and -march=native would
    # SIGILL on an older CPU at runtime with no fallback. The kernels are
    # memcpy/hash-bound; ISA-specific vectorization buys nothing here.
    # Opt in explicitly via RGTPU_NATIVE_CXXFLAGS for same-host builds.
    extra = os.environ.get("RGTPU_NATIVE_CXXFLAGS", "").split()
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", *extra,
           _SRC, "-o", tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            if verbose:
                print(f"native build failed:\n{r.stderr}", file=sys.stderr)
            os.unlink(tmp)
            return None
        os.replace(tmp, out)
        return out
    except Exception as e:  # compiler missing/hung — fall back silently
        if verbose:
            print(f"native build error: {e}", file=sys.stderr)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


if __name__ == "__main__":
    p = build(verbose=True)
    if p is None:
        print("BUILD FAILED (NumPy fallbacks will be used)", file=sys.stderr)
        sys.exit(1)  # fail image builds that expect the fastpath baked in
    print(p)
