"""Native (C++) host-side fastpath: tokenization + batch assembly.

See fastpath.cpp for the kernels, build.py for the on-demand g++ build,
binding.py for the ctypes surface. All callers degrade to NumPy/Python
automatically when no toolchain is present (``available()`` is False) or
when ``RGTPU_NO_NATIVE=1``.
"""

from .binding import (BpeMergeTable, available, bpe_encode_words, encode_lut,
                      gather_batch)

__all__ = ["BpeMergeTable", "available", "bpe_encode_words", "encode_lut",
           "gather_batch"]
