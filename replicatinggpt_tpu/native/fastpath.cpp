// Native host-side data path: the framework's C++ runtime layer.
//
// The reference has zero native components (SURVEY.md §2.0) — its host data
// path is pure Python/PyTorch (get_batch GPT1.py:75-83, DataLoaderLite
// GPT-2.py:187-213, tiktoken corpus encode GPT-2.py:192-196). On TPU the
// device side is XLA-compiled, so the only place framework code can burn
// host CPU (and stall the input pipeline feeding the chips) is exactly this
// path. These kernels keep it off the Python interpreter:
//
//   rg_encode_lut     byte->id table lookup (char-level tokenization)
//   rg_bpe_encode     greedy lowest-rank BPE merge loop over pre-split words
//   rg_gather_batch   fused (B,T) x/y window gather for batch assembly
//
// Compiled on demand by build.py (g++ -O3 -shared -fPIC), bound via ctypes
// (binding.py). Every entry point has a NumPy fallback with identical
// output, bit-for-bit — tests/test_native.py asserts the parity.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

extern "C" {

// text: n raw bytes; lut: 256 entries mapping byte -> id (-1 = unmapped,
// byte passes through as id 0 and the count of unmapped bytes is returned
// so the caller can reject non-ASCII corpora and fall back).
long rg_encode_lut(const uint8_t* text, long n, const int32_t* lut,
                   int32_t* out) {
  long bad = 0;
  for (long i = 0; i < n; ++i) {
    int32_t v = lut[text[i]];
    if (v < 0) {
      ++bad;
      v = 0;
    }
    out[i] = v;
  }
  return bad;
}

// data: token stream of length n; offsets: B window starts (each in
// [0, n-T-1]); writes x[b,t] = data[off_b + t], y[b,t] = data[off_b + t + 1].
void rg_gather_batch(const int32_t* data, long n, const int64_t* offsets,
                     int B, int T, int32_t* x, int32_t* y) {
  (void)n;
  for (int b = 0; b < B; ++b) {
    const int32_t* src = data + offsets[b];
    std::memcpy(x + (long)b * T, src, sizeof(int32_t) * T);
    std::memcpy(y + (long)b * T, src + 1, sizeof(int32_t) * T);
  }
}

namespace {

// Merge-table cache: table_id -> ((left_id,right_id) -> (rank, new_id)).
// table_id is an opaque token minted by the Python side, unique per
// BpeMergeTable instance for the life of the process (binding.py) — never
// a pointer, since the allocator can hand a new table a freed buffer's
// address. g_mutex serializes everything: ctypes releases the GIL during
// the call, so concurrent encodes would otherwise race on the cache.
struct MergeCache {
  std::mutex mutex;
  std::unordered_map<int64_t,
                     std::unordered_map<uint64_t,
                                        std::pair<int32_t, int32_t>>> tables;
};

MergeCache g_cache;

inline uint64_t pack(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

}  // namespace

// Greedy BPE over one flattened batch of words.
//
//   units:      concatenated byte-ids of every word
//   word_off:   n_words+1 offsets into units
//   left/right/new_id: M merge rules (row index ascending == priority)
//   table_id:   process-unique cache token for this rule set
//   out:        capacity >= len(units); returns number of ids written
//
// Semantics identical to ByteBPETokenizer._bpe_word (tokenizers.py:168-181):
// repeatedly merge the lowest-rank adjacent pair (leftmost on ties, which
// the (rank, index) min in Python also picks) until no pair has a rank.
long rg_bpe_encode(const int32_t* units, const int64_t* word_off,
                   long n_words, const int32_t* left, const int32_t* right,
                   const int32_t* new_id, long n_merges, int64_t table_id,
                   int32_t* out) {
  std::lock_guard<std::mutex> lock(g_cache.mutex);
  auto& table = g_cache.tables[table_id];
  if (table.empty() && n_merges > 0) {
    table.reserve(static_cast<size_t>(n_merges) * 2);
    for (long i = 0; i < n_merges; ++i) {
      table.emplace(pack(left[i], right[i]),
                    std::make_pair((int32_t)i, new_id[i]));
    }
  }

  long written = 0;
  std::vector<int32_t> buf;
  for (long w = 0; w < n_words; ++w) {
    const long lo = word_off[w], hi = word_off[w + 1];
    buf.assign(units + lo, units + hi);
    while (buf.size() > 1) {
      int32_t best_rank = INT32_MAX, best_new = -1;
      long best_i = -1;
      for (long i = 0; i + 1 < (long)buf.size(); ++i) {
        auto it = table.find(pack(buf[i], buf[i + 1]));
        if (it != table.end() && it->second.first < best_rank) {
          best_rank = it->second.first;
          best_new = it->second.second;
          best_i = i;
        }
      }
      if (best_i < 0) break;
      buf[best_i] = best_new;
      buf.erase(buf.begin() + best_i + 1);
    }
    for (int32_t id : buf) out[written++] = id;
  }
  return written;
}

// Release a cached merge table (called from BpeMergeTable.__del__ so
// dropped tokenizers don't leak their C++ map).
void rg_bpe_free_table(int64_t table_id) {
  std::lock_guard<std::mutex> lock(g_cache.mutex);
  g_cache.tables.erase(table_id);
}

}  // extern "C"
