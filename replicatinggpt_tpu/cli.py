"""Command-line interface.

The reference has no CLI — both scripts train at import time with
module-global hyperparameters (SURVEY.md §1 L6, §8-Q9). This CLI exposes
every pipeline as a subcommand over the preset/override config system:

    python -m replicatinggpt_tpu train    --preset char-gpt
    python -m replicatinggpt_tpu generate --preset char-gpt --checkpoint ...
    python -m replicatinggpt_tpu import-hf --model-type gpt2
    python -m replicatinggpt_tpu eval     --preset char-gpt --checkpoint ...
    python -m replicatinggpt_tpu export-torch --preset char-gpt \
        --checkpoint-dir ... --out model.pth
    python -m replicatinggpt_tpu serve-replay --preset char-gpt \
        --n-requests 64 --pool-size 8
"""

from __future__ import annotations

import argparse
import sys

from .config import add_config_flags, config_from_args

#: (dest, flag) pairs for every ENGINE-shape flag registered by
#: add_engine_flags — the serving analogue of
#: config.MODEL_OVERRIDE_FLAGS, kept adjacent to the registration for
#: the same reason: `serve --multiproc` respawns workers with
#: engine_forward_args(), so a flag missing here means a fleet of
#: workers silently serving a DIFFERENT engine shape (pool, pages,
#: decode window, mesh slice) than the operator asked for. Round-trip
#: pinned in tests/test_serve_mesh.py.
ENGINE_FORWARD_FLAGS = (
    ("pool_size", "--pool-size"),
    ("max_queue", "--max-queue"),
    ("prefill_chunk", "--prefill-chunk"),
    ("page_size", "--page-size"),
    ("max_pages", "--max-pages"),
    ("n_pages", "--n-pages"),
    ("decode_window", "--decode-window"),
    ("mesh_shape", "--mesh-shape"),
    ("kv_quant", "--kv-quant"),
    ("weight_quant", "--weight-quant"),
    ("quant_granularity", "--quant-granularity"),
    ("act_quant", "--act-quant"),
)
#: store_true engine switches, forwarded only when set
ENGINE_FORWARD_SWITCHES = (("no_prefix_cache", "--no-prefix-cache"),
                           ("decode_window_auto", "--decode-window-auto"),
                           ("paged_kernel", "--paged-kernel"))


def add_engine_flags(p: argparse.ArgumentParser) -> None:
    """Engine-shape knobs shared by serve-replay / serve / serve-worker
    (one registration — the three parsers must agree or the multiproc
    forwarding in ``engine_forward_args`` breaks)."""
    p.add_argument("--pool-size", type=int, default=8,
                   help="KV-cache slots pre-allocated at engine start")
    p.add_argument("--max-queue", type=int, default=64,
                   help="admission queue bound (backpressure past it)")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="prompt tokens per prefill dispatch "
                        "(0 = min(64, block_size))")
    p.add_argument("--page-size", type=int, default=0,
                   help="tokens per KV-cache page (0 = min(16, "
                        "block_size)); see docs/serving.md")
    p.add_argument("--max-pages", type=int, default=0,
                   help="logical KV pages per slot (0 = "
                        "ceil(block_size / page_size)); capping below "
                        "that bounds per-request KV length")
    p.add_argument("--n-pages", type=int, default=0,
                   help="physical KV pages in the pool (0 = "
                        "pool_size * pages-per-slot — the contiguous "
                        "pool's HBM exactly; fewer pages shrinks HBM "
                        "and admission gates on free pages)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable radix prefix reuse (pages only) — "
                        "the A/B arm for prefix-hit TTFT claims")
    p.add_argument("--decode-window", type=int, default=1,
                   help="decode steps rolled into ONE jitted dispatch "
                        "at steady state (async engine; 1 = blocked "
                        "step-per-dispatch loop). Continuous windows: "
                        "admissions ride mixed prefill+decode "
                        "dispatches and deadlines/cancels land as "
                        "on-device lifecycle masks, so only "
                        "speculative verify/re-probe still breaks a "
                        "window — see docs/serving.md#async-engine")
    p.add_argument("--decode-window-auto", action="store_true",
                   help="auto-tune the window size from the live "
                        "host-vs-device dispatch split: bounded "
                        "additive increase over power-of-two buckets "
                        "up to --decode-window (all bucket programs "
                        "compiled at engine start, so tuning never "
                        "recompiles)")
    p.add_argument("--mesh-shape", default="1x1",
                   help="serving mesh DATAxMODEL (e.g. 2x2): run the "
                        "engine GSPMD-sharded over a (data, model) "
                        "device mesh — the paged KV pool's page axis "
                        "shards over data (aggregate page capacity "
                        "multiplier at fixed per-chip HBM), Megatron "
                        "TP over model (attention/MLP FLOPs per "
                        "step). 1x1 = single device. See "
                        "docs/serving.md#sharded-serving")
    p.add_argument("--kv-quant", default="none",
                   choices=["none", "int8", "fp8"],
                   help="paged KV page storage precision: int8/fp8 "
                        "pages + per-row scale metadata roughly halve "
                        "bytes/page, so at fixed HBM --n-pages can "
                        "roughly double (pages are the admission "
                        "currency; size with "
                        "serve.pages.n_pages_for_hbm). Dequant runs "
                        "inside the paged decode kernels / the XLA "
                        "gather. See docs/serving.md#quantization")
    p.add_argument("--weight-quant", default="none",
                   choices=["none", "int8", "fp8"],
                   help="block matmul kernel precision: absmax-per-"
                        "output-channel scales with dequant fused "
                        "into the matmuls (quant/weights.py); a "
                        "serialized calibration next to "
                        "--checkpoint-dir is applied when present, "
                        "else computed (and saved) at startup")
    p.add_argument("--paged-kernel", action="store_true",
                   help="opt into the unified Pallas paged-attention "
                        "kernel family for EVERY engine step (decode, "
                        "mixed prefill+decode windows, speculative "
                        "verify; shard_map-wrapped on a >1 mesh). The "
                        "route decision is static per engine and "
                        "exported — metrics_summary()['kernel_route'] "
                        "names any envelope gate that forced XLA")
    p.add_argument("--quant-granularity", default="page",
                   choices=["page", "head"],
                   help="KV scale granularity: 'page' = one f32 scale "
                        "per written row, 'head' = one per (row, head) "
                        "— tighter for outlier heads at H x the "
                        "metadata; both dequant in-kernel on the "
                        "Pallas route")
    p.add_argument("--act-quant", default="none",
                   choices=["none", "int8"],
                   help="W8A8: quantize activation rows to int8 "
                        "(absmax per row) into the int8 weight "
                        "matmuls — requires --weight-quant int8; "
                        "halves the activation operand and feeds the "
                        "MXU a native int8 x int8 contraction")


def engine_forward_args(args: argparse.Namespace) -> list:
    """Reconstruct the add_engine_flags CLI arguments present on
    ``args`` so `serve --multiproc` can respawn workers with the exact
    engine shape (the config_override_args pattern)."""
    out: list = []
    for dest, flag in ENGINE_FORWARD_FLAGS:
        out += [flag, str(getattr(args, dest))]
    for dest, flag in ENGINE_FORWARD_SWITCHES:
        if getattr(args, dest, False):
            out.append(flag)
    return out


def engine_config_from_args(args: argparse.Namespace):
    """EngineConfig from an add_engine_flags parse. A mesh shape the
    process cannot satisfy downgrades to 1x1 with a warning (the
    ``_build_mesh_if_needed`` convention: a dev box run of a pod-slice
    command should degrade, not die)."""
    from .parallel.mesh import parse_mesh_shape, resolve_mesh_shape
    from .serve import EngineConfig
    d, m = parse_mesh_shape(args.mesh_shape)
    if d * m > 1:
        import jax
        d, m = resolve_mesh_shape(
            args.mesh_shape, len(jax.devices()),
            warn=lambda msg: print("warning: " + msg, file=sys.stderr))
    return EngineConfig(pool_size=args.pool_size,
                        max_queue=args.max_queue,
                        prefill_chunk=args.prefill_chunk,
                        page_size=args.page_size,
                        max_pages=args.max_pages, n_pages=args.n_pages,
                        prefix_cache=not args.no_prefix_cache,
                        paged_kernel=args.paged_kernel,
                        decode_window=args.decode_window,
                        decode_window_auto=args.decode_window_auto,
                        mesh_data=d, mesh_model=m,
                        kv_quant=args.kv_quant,
                        weight_quant=args.weight_quant,
                        quant_granularity=args.quant_granularity,
                        act_quant=args.act_quant)


def _build_mesh_if_needed(cfg):
    import jax
    if cfg.mesh.n_devices <= 1 and not cfg.mesh.fsdp:
        return None
    from .parallel.mesh import make_mesh
    n = cfg.mesh.n_devices
    if len(jax.devices()) < n:
        print(f"warning: mesh wants {n} devices, have "
              f"{len(jax.devices())}; running unsharded", file=sys.stderr)
        return None
    return make_mesh(cfg.mesh)


def _apply_rng_impl(args) -> None:
    if getattr(args, "rng_impl", None):
        import jax
        jax.config.update("jax_default_prng_impl", args.rng_impl)


def cmd_train(args) -> int:
    _apply_rng_impl(args)
    if args.coordinator or args.num_processes:
        from .parallel.distributed import initialize
        pi, pn = initialize(args.coordinator, args.num_processes,
                            args.process_id)
        print(f"distributed: process {pi}/{pn}", file=sys.stderr)
    cfg = config_from_args(args)
    from .train.checkpoint import CheckpointManager
    from .train.runner import train
    from .utils.logging import StepLogger
    logger = StepLogger(jsonl_path=args.log_jsonl)
    ck = (CheckpointManager(args.checkpoint_dir)
          if args.checkpoint_dir else None)
    mesh = _build_mesh_if_needed(cfg)
    if args.profile_port:
        from .utils.profiling import start_server
        start_server(args.profile_port)
        print(f"profiler server on :{args.profile_port}", file=sys.stderr)
    # graceful preemption: SIGTERM/SIGINT finish the in-flight dispatch,
    # checkpoint, and exit 0 — resume later with --resume
    import signal
    import threading
    stop = threading.Event()

    def _on_signal(signum, frame):
        if stop.is_set() and signum == signal.SIGINT:
            # second Ctrl+C: the user wants out NOW (e.g. a wedged TPU
            # tunnel where no further step will ever complete)
            signal.signal(signal.SIGINT, signal.default_int_handler)
            raise KeyboardInterrupt
        stop.set()

    telemetry = None
    if args.trace_out:
        from .utils.telemetry import Telemetry
        telemetry = Telemetry()
    prev_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        prev_handlers[sig] = signal.signal(sig, _on_signal)
    try:
        res = train(cfg, mesh=mesh, logger=logger, checkpoint_manager=ck,
                    resume=args.resume, profile_dir=args.profile_dir,
                    profile_start=args.profile_start,
                    profile_steps=args.profile_steps, stop_event=stop,
                    telemetry=telemetry)
    finally:
        for sig, h in prev_handlers.items():
            signal.signal(sig, h)
        if telemetry is not None:
            n = telemetry.export_chrome_trace(args.trace_out)
            telemetry.close()
            print(f"telemetry: {n} trace events -> {args.trace_out} "
                  f"(open in Perfetto)", file=sys.stderr)
    if args.sample_after:
        _sample(res.state.params, cfg, res.tokenizer, args.sample_tokens,
                mesh=mesh)
    if ck:
        ck.wait()
    return 0


def _sample(params, cfg, tokenizer, n_tokens: int, prompt_text: str = None,
            top_k: int = 0, top_p: float = 0.0, temperature: float = 1.0,
            mesh=None) -> None:
    import jax.numpy as jnp
    import numpy as np
    from .sample import GenerateConfig, generate, shard_for_decode
    if prompt_text:
        prompt = np.asarray([tokenizer.encode(prompt_text)], np.int32)
    else:
        # the reference's zero-context start (GPT1.py:235)
        prompt = np.zeros((1, 1), np.int32)
    prompt = jnp.asarray(prompt)
    if mesh is not None:
        # TP-sharded decode: Megatron specs over 'model', replicated over
        # 'data' (see sample.generate.shard_for_decode)
        params, prompt = shard_for_decode(params, prompt, cfg.model, mesh,
                                          cfg.mesh)
    toks = generate(params, prompt, cfg.model,
                    GenerateConfig(max_new_tokens=n_tokens, top_k=top_k,
                                   top_p=top_p, temperature=temperature))
    print(tokenizer.decode(np.asarray(toks)[0].tolist()))


def cmd_generate(args) -> int:
    _apply_rng_impl(args)
    import jax
    cfg = config_from_args(args)
    from .data.dataset import load_corpus
    from .tokenizers import get_tokenizer
    from .train.checkpoint import CheckpointManager
    from .train.runner import _resolve_vocab
    from .train.state import create_train_state
    text = load_corpus(cfg.dataset)
    tokenizer = get_tokenizer(cfg.tokenizer, corpus_text=text)
    cfg = _resolve_vocab(cfg, tokenizer)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed),
                               cfg.model, cfg.train)
    if args.checkpoint_dir:
        ck = CheckpointManager(args.checkpoint_dir)
        restored = ck.restore_latest(state)
        if restored is None:
            print("no checkpoint found; sampling from random init",
                  file=sys.stderr)
        else:
            state = restored
    _sample(state.params, cfg, tokenizer, args.sample_tokens,
            prompt_text=args.prompt, top_k=args.top_k, top_p=args.top_p,
            temperature=args.temperature, mesh=_build_mesh_if_needed(cfg))
    return 0


def cmd_import_hf(args) -> int:
    from .interop.hf import from_pretrained
    params, mcfg = from_pretrained(args.model_type)
    from .models.gpt import param_count
    print(f"imported {args.model_type}: {param_count(params):,} params, "
          f"{mcfg.n_layer}L/{mcfg.n_head}H/{mcfg.n_embd}C")
    if args.save_dir:
        import jax.numpy as jnp
        import jax
        from .train.checkpoint import CheckpointManager
        from .train.state import TrainState
        state = TrainState(step=jnp.zeros((), jnp.int32),
                           params=params, opt_state=(),
                           rng=jax.random.PRNGKey(0))
        ck = CheckpointManager(args.save_dir)
        ck.save(state, wait=True)
        print(f"saved to {args.save_dir}")
    return 0


def cmd_export_torch(args) -> int:
    """Write the reference's durable artifact — a torch ``state_dict``
    file (``torch.save(m.state_dict(), 'model.pth')``,
    /root/reference/GPT1.py:239-241) — from a framework checkpoint.
    The tensors land in :class:`~.reference_torch.RefGPT`'s layout
    ((in, out) kernels, applied as ``x @ W``), so
    ``RefGPT(cfg).load_state_dict(torch.load(out))`` reproduces the
    checkpointed model bit-for-bit in torch (round-trip pinned in
    tests/test_cli.py). Closes the import/export asymmetry: import-hf
    brings torch weights in, this takes them out."""
    _apply_rng_impl(args)
    import jax
    import torch
    cfg = config_from_args(args)
    from .data.dataset import load_corpus
    from .reference_torch import RefGPT, params_to_torch
    from .tokenizers import get_tokenizer
    from .train.checkpoint import CheckpointManager
    from .train.runner import _resolve_vocab
    from .train.state import create_train_state
    text = load_corpus(cfg.dataset)
    tokenizer = get_tokenizer(cfg.tokenizer, corpus_text=text)
    cfg = _resolve_vocab(cfg, tokenizer)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed),
                               cfg.model, cfg.train)
    if args.checkpoint_dir:
        ck = CheckpointManager(args.checkpoint_dir)
        restored = ck.restore_latest(state)
        if restored is None:
            print("no checkpoint found; exporting random init",
                  file=sys.stderr)
        else:
            state = restored
    else:
        print("no --checkpoint-dir; exporting random init", file=sys.stderr)
    model = params_to_torch(jax.device_get(state.params), RefGPT(cfg.model))
    with open(args.out, "wb") as f:
        torch.save(model.state_dict(), f)
    n_params = sum(p.numel() for p in model.parameters())
    print(f"exported {n_params:,} params (step "
          f"{int(state.step)}) to {args.out}")
    return 0


def cmd_serve_replay(args) -> int:
    """Replay a synthetic Poisson request trace through the
    continuous-batching engine (serve/) and print the serving metrics
    summary — the offline stand-in for real traffic (zero-egress image).
    Random-init params by default; --checkpoint-dir serves a trained
    model (token ids are synthetic either way, so no tokenizer/corpus
    is needed)."""
    _apply_rng_impl(args)
    import json

    import jax

    from .config import config_from_args
    from .serve import EngineConfig, ReplayConfig, format_summary, run_replay
    from .train.state import create_train_state
    cfg = config_from_args(args)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed),
                               cfg.model, cfg.train)
    if args.checkpoint_dir:
        from .train.checkpoint import CheckpointManager
        restored = CheckpointManager(args.checkpoint_dir).restore_latest(state)
        if restored is None:
            print("no checkpoint found; serving random init",
                  file=sys.stderr)
        else:
            state = restored
    rcfg = ReplayConfig(
        n_requests=args.n_requests, rate=args.rate, seed=args.seed or 0,
        prompt_len_min=args.prompt_len_min,
        prompt_len_max=args.prompt_len_max or cfg.model.block_size // 2,
        max_new_tokens=args.request_max_new_tokens, greedy=args.greedy,
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        deadline_s=args.deadline_s, prompt_mode=args.prompt_mode,
        shared_prefix_len=args.shared_prefix_len,
        spec=args.spec, spec_k=args.spec_k, spec_ngram=args.spec_ngram)
    ecfg = engine_config_from_args(args)
    if ecfg.weight_quant != "none":
        # the serialized-calibration workflow: reuse the scales next to
        # the checkpoint, or calibrate + save them now (quant/weights)
        from .quant.weights import prepare_params
        state = state._replace(params=prepare_params(
            state.params, cfg.model, ecfg.weight_quant,
            checkpoint_dir=args.checkpoint_dir,
            log=lambda m: print(m, file=sys.stderr)))
    draft_params = draft_cfg = None
    if rcfg.spec == "model":
        from .models.gpt import init_params, param_count
        from .serve import draft_config_from_preset
        draft_cfg = draft_config_from_preset(cfg.model, args.draft_model)
        draft_params = init_params(jax.random.PRNGKey(cfg.train.seed + 1),
                                   draft_cfg)
        print(f"draft model: {args.draft_model} -> "
              f"{draft_cfg.n_layer}L/{draft_cfg.n_head}H/"
              f"{draft_cfg.n_embd}C ({param_count(draft_params):,} params, "
              f"random init)", file=sys.stderr)
    dev = jax.devices()[0]
    mesh_note = (f", mesh {ecfg.mesh_data}x{ecfg.mesh_model}"
                 if ecfg.mesh_data * ecfg.mesh_model > 1 else "")
    print(f"serve-replay: {rcfg.n_requests} requests @ {rcfg.rate}/s, "
          f"pool {ecfg.pool_size}, queue {ecfg.max_queue}, "
          f"spec {rcfg.spec} (k={rcfg.spec_k}){mesh_note}, "
          f"model {cfg.model.n_layer}L/{cfg.model.n_head}H/"
          f"{cfg.model.n_embd}C on {dev.platform} ({dev.device_kind})",
          file=sys.stderr)
    summary = run_replay(state.params, cfg.model, rcfg, ecfg,
                         draft_params=draft_params, draft_cfg=draft_cfg,
                         trace_out=args.trace_out,
                         metrics_timeline=args.metrics_timeline,
                         metrics_timeline_interval_s=(
                             args.metrics_timeline_interval),
                         metrics_out=args.metrics_out,
                         profile_dir=args.profile_dir,
                         profile_start=args.profile_start,
                         profile_steps=args.profile_steps)
    print(format_summary(summary))
    for k, v in summary.get("artifacts", {}).items():
        print(f"artifact {k}: {v}", file=sys.stderr)
    if args.json:
        print(json.dumps(summary))
    return 0


def cmd_serve(args) -> int:
    """The fleet front door: N engine replicas behind the prefix-
    affinity router (serve/router.py), exposed over HTTP/SSE
    (serve/http.py) — submit/stream/cancel/healthz/readyz/metrics.
    Binds loopback by default (the zero-egress image takes no outside
    traffic; this is the ingress path's real implementation, exercised
    by tests and local clients). Ctrl-C shuts down cleanly, closing
    the per-replica crash journals.

    ``--multiproc`` runs the replicas as real worker PROCESSES
    (serve-worker subcommand) under the process supervisor
    (faults/procsup.py): each worker owns its own engine and an
    exclusively-locked journal in --journal-dir; the router speaks
    serve/rpc.py to them, the supervisor restarts the dead with
    backoff and quarantines past the restart budget
    (docs/serving.md#deployment)."""
    _apply_rng_impl(args)
    import asyncio

    from .serve.http import ServeApp
    from .serve.router import RouterConfig

    import os

    ledger = args.ledger
    if ledger is None and args.multiproc and args.journal_dir:
        ledger = os.path.join(args.journal_dir, "router_ledger.jsonl")
    rcfg = RouterConfig(n_replicas=args.replicas,
                        journal_dir=args.journal_dir,
                        ledger_path=ledger,
                        ledger_fsync=args.ledger_fsync,
                        affinity=not args.no_affinity,
                        wedge_budget_s=args.wedge_budget_s,
                        wedge_patience=args.wedge_patience,
                        step_timeout_s=args.step_timeout_s)
    telemetry = None
    if args.trace_out or args.trace_jsonl:
        from .utils.telemetry import Telemetry
        telemetry = Telemetry(jsonl_path=args.trace_jsonl)
    supervisor = None
    if args.multiproc:
        if not args.journal_dir:
            print("--multiproc requires --journal-dir (the base "
                  "directory for per-worker PRIVATE journal dirs and "
                  "the router's own ledger — nothing in it is shared "
                  "between processes)", file=sys.stderr)
            return 2
        from .faults.procsup import (AutoscaleConfig, SupervisorConfig,
                                     make_worker_specs, spawn_fleet,
                                     worker_spec_factory)
        # the workers must build the SAME model the operator asked
        # for: forward every set model-override flag (the serve-worker
        # parser takes the full add_config_flags set too) — silently
        # serving the preset's defaults would be a different model.
        # The flag list lives NEXT TO add_config_flags
        # (config.MODEL_OVERRIDE_FLAGS) so new flags can't fall out.
        from .config import config_override_args
        config_args = (["--preset", args.preset]
                       + config_override_args(args))
        if args.rng_impl is not None:
            config_args += ["--rng-impl", args.rng_impl]
        # the full engine shape — pool/pages/window/MESH SLICE — rides
        # the same pinned plumbing as the model overrides above
        # (ENGINE_FORWARD_FLAGS next to add_engine_flags), so each
        # worker process builds exactly the engine the operator asked
        # for, mesh included
        engine_args = engine_forward_args(args)
        if args.no_fsync:
            engine_args.append("--no-fsync")
        if args.checkpoint_dir:
            engine_args += ["--checkpoint-dir", args.checkpoint_dir]
        specs = make_worker_specs(args.replicas, args.journal_dir,
                                  config_args, engine_args)
        # pin the fleet's expected engine shape from THIS process's
        # parse of the same flags the workers receive: a worker whose
        # build resolves a different model/engine is rejected at
        # registration with RpcProtocolError, never served traffic
        from .serve.rpc import engine_shape_hash
        expect = engine_shape_hash(config_from_args(args).model,
                                   engine_config_from_args(args))
        autoscale = spec_factory = None
        if args.autoscale_max > 0:
            autoscale = AutoscaleConfig(min_workers=args.autoscale_min,
                                        max_workers=args.autoscale_max)
            spec_factory = worker_spec_factory(args.journal_dir,
                                               config_args, engine_args)
        print(f"spawning {args.replicas} worker process(es); waiting "
              f"for warmup + RPC registration (expect shape {expect})",
              file=sys.stderr)
        router, supervisor = spawn_fleet(
            specs, rcfg,
            SupervisorConfig(restart_budget=args.restart_budget,
                             expect_shape_hash=expect),
            telemetry=telemetry, autoscale=autoscale,
            spec_factory=spec_factory, listen_host=args.listen_host)
        if args.listen_host not in ("127.0.0.1", "localhost"):
            print(f"fleet up: workers on other hosts join via "
                  f"`serve-worker --router-addr "
                  f"<this-host>:{supervisor.listener.port}`",
                  file=sys.stderr)
        else:
            print(f"fleet up: registration on "
                  f"{supervisor.router_addr} (loopback — pass "
                  f"`--listen-host 0.0.0.0` to accept workers from "
                  f"other hosts)", file=sys.stderr)
    else:
        import jax

        from .serve import Router
        from .train.state import create_train_state
        cfg = config_from_args(args)
        state = create_train_state(jax.random.PRNGKey(cfg.train.seed),
                                   cfg.model, cfg.train)
        if args.checkpoint_dir:
            from .train.checkpoint import CheckpointManager
            restored = (CheckpointManager(args.checkpoint_dir)
                        .restore_latest(state))
            if restored is None:
                print("no checkpoint found; serving random init",
                      file=sys.stderr)
            else:
                state = restored
        in_ecfg = engine_config_from_args(args)
        if in_ecfg.weight_quant != "none":
            from .quant.weights import prepare_params
            state = state._replace(params=prepare_params(
                state.params, cfg.model, in_ecfg.weight_quant,
                checkpoint_dir=args.checkpoint_dir,
                log=lambda m: print(m, file=sys.stderr)))
        router = Router(state.params, cfg.model, rcfg, in_ecfg,
                        telemetry=telemetry)
    rate_limit = None
    if args.rate_limit_rps > 0:
        from .serve.http import RateLimitConfig
        rate_limit = RateLimitConfig(rps=args.rate_limit_rps,
                                     burst=args.rate_limit_burst)
    app = ServeApp(router, idle_timeout_s=args.idle_timeout_s,
                   supervisor=supervisor, rate_limit=rate_limit)
    rc = 0
    try:
        asyncio.run(app.serve_forever(args.host, args.port))
    except KeyboardInterrupt:
        print("shutting down", file=sys.stderr)
    except asyncio.CancelledError:
        # the driver task died (ServeApp._on_driver_done printed the
        # traceback and closed the server out from under serve_forever)
        rc = 1
    finally:
        if supervisor is not None:
            supervisor.stop_all()
        router.close()
        if telemetry is not None:
            if args.trace_out:
                n = telemetry.export_chrome_trace(args.trace_out)
                print(f"telemetry: {n} trace events -> {args.trace_out}",
                      file=sys.stderr)
            telemetry.close()
            if args.trace_jsonl:
                print(f"telemetry: event sink -> {args.trace_jsonl}",
                      file=sys.stderr)
    return rc


def cmd_serve_worker(args) -> int:
    """One fleet worker process (serve/worker.py): builds + warms one
    engine, opens its exclusively-locked crash journal, replays the
    previous incarnation's unfinished requests, then serves the
    serve/rpc.py protocol on loopback until the router shuts it down
    (or something kills it — which is the point: the journal + the
    router's delivery ledger make that survivable)."""
    _apply_rng_impl(args)
    from .serve.worker import run_worker
    return run_worker(args)


def cmd_eval(args) -> int:
    _apply_rng_impl(args)
    import jax
    cfg = config_from_args(args)
    from .data.dataset import TokenDataset, load_corpus
    from .data.loader import make_batcher
    from .tokenizers import get_tokenizer
    from .train.checkpoint import CheckpointManager
    from .train.runner import _resolve_vocab
    from .train.state import create_train_state
    from .train.steps import estimate_loss, make_eval_scan, make_eval_step
    text = load_corpus(cfg.dataset)
    tokenizer = get_tokenizer(cfg.tokenizer, corpus_text=text)
    cfg = _resolve_vocab(cfg, tokenizer)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed),
                               cfg.model, cfg.train)
    if args.checkpoint_dir:
        state = (CheckpointManager(args.checkpoint_dir)
                 .restore_latest(state) or state)
    ds = TokenDataset.from_text(text, tokenizer, cfg.train.val_fraction)
    batchers = {
        "train": make_batcher("random", ds.train, cfg.train.batch_size,
                              cfg.model.block_size, seed=1),
        "val": make_batcher("random", ds.val, cfg.train.batch_size,
                            cfg.model.block_size, seed=2),
    }
    out = estimate_loss(state.params, batchers, make_eval_step(cfg.model),
                        cfg.train.eval_iters,
                        eval_scan=make_eval_scan(cfg.model))
    print(f"train loss {out['train']:.4f}, val loss = {out['val']:.4f}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="replicatinggpt_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    pt = sub.add_parser("train", help="train a model")
    add_config_flags(pt)
    pt.add_argument("--checkpoint-dir", default=None)
    pt.add_argument("--resume", action="store_true")
    pt.add_argument("--log-jsonl", default=None)
    pt.add_argument("--sample-after", action="store_true",
                    help="print a sample after training (GPT1.py:235-236)")
    pt.add_argument("--sample-tokens", type=int, default=500)
    pt.add_argument("--coordinator", default=None,
                    help="multi-host coordinator address host:port "
                         "(jax.distributed.initialize); TPU pods usually "
                         "auto-detect and need none of these")
    pt.add_argument("--num-processes", type=int, default=None)
    pt.add_argument("--process-id", type=int, default=None)
    pt.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace of a few hot-loop "
                         "steps here (view in TensorBoard/Perfetto)")
    pt.add_argument("--profile-start", type=int, default=10)
    pt.add_argument("--profile-steps", type=int, default=5)
    pt.add_argument("--profile-port", type=int, default=0,
                    help="start a live profiler server on this port")
    pt.add_argument("--trace-out", default=None,
                    help="write a Perfetto-loadable Chrome trace of the "
                         "host timeline (dispatch/eval spans, checkpoint "
                         "markers) here — the host half of --profile-dir")
    pt.add_argument("--rng-impl", default=None,
                    choices=["threefry2x32", "rbg"],
                    help="dropout PRNG; 'rbg' uses the TPU hardware "
                         "generator (~15%% faster steps at dropout 0.2)")
    pt.set_defaults(fn=cmd_train)

    pg = sub.add_parser("generate", help="sample from a model")
    add_config_flags(pg)
    pg.add_argument("--rng-impl", default=None,
                    choices=["threefry2x32", "rbg"],
                    help="must match the checkpoint's training run")
    pg.add_argument("--checkpoint-dir", default=None)
    pg.add_argument("--prompt", default=None)
    pg.add_argument("--sample-tokens", type=int, default=500)
    pg.add_argument("--top-k", type=int, default=0)
    pg.add_argument("--top-p", type=float, default=0.0,
                    help="nucleus sampling mass; 0 = off")
    pg.add_argument("--temperature", type=float, default=1.0)
    pg.set_defaults(fn=cmd_generate)

    pi = sub.add_parser("import-hf", help="import HF GPT-2 weights")
    pi.add_argument("--model-type", default="gpt2",
                    choices=["gpt2", "gpt2-medium", "gpt2-large", "gpt2-xl"])
    pi.add_argument("--save-dir", default=None)
    pi.set_defaults(fn=cmd_import_hf)

    px = sub.add_parser("export-torch",
                        help="export a checkpoint as a torch state_dict "
                             "(the reference's model.pth artifact)")
    add_config_flags(px)
    px.add_argument("--checkpoint-dir", default=None)
    px.add_argument("--out", default="model.pth")
    px.set_defaults(fn=cmd_export_torch)

    ps = sub.add_parser("serve-replay",
                        help="replay a synthetic Poisson request trace "
                             "through the continuous-batching serving "
                             "engine and report TTFT/throughput/occupancy")
    add_config_flags(ps)
    ps.add_argument("--rng-impl", default=None,
                    choices=["threefry2x32", "rbg"])
    ps.add_argument("--checkpoint-dir", default=None)
    ps.add_argument("--n-requests", type=int, default=64)
    ps.add_argument("--rate", type=float, default=200.0,
                    help="mean Poisson arrival rate, requests/sec")
    add_engine_flags(ps)
    ps.add_argument("--shared-prefix-len", type=int, default=0,
                    help="--prompt-mode shared_prefix: common prefix "
                         "length (0 = prompt-len-max // 2)")
    ps.add_argument("--prompt-len-min", type=int, default=1)
    ps.add_argument("--prompt-len-max", type=int, default=0,
                    help="0 = block_size // 2")
    ps.add_argument("--request-max-new-tokens", type=int, default=16)
    ps.add_argument("--greedy", action="store_true")
    ps.add_argument("--temperature", type=float, default=1.0)
    ps.add_argument("--top-k", type=int, default=20)
    ps.add_argument("--top-p", type=float, default=0.0)
    ps.add_argument("--deadline-s", type=float, default=0.0,
                    help="per-request deadline after arrival (0 = none)")
    ps.add_argument("--spec", default="off",
                    choices=["off", "ngram", "model"],
                    help="speculative decoding drafter: host-side n-gram "
                         "prompt lookup (no extra params) or a small "
                         "random-init draft model (--draft-model preset)")
    ps.add_argument("--spec-k", type=int, default=4,
                    help="drafted tokens per slot per step (static: one "
                         "verify program per k)")
    ps.add_argument("--spec-ngram", type=int, default=3,
                    help="n-gram drafter match width")
    ps.add_argument("--draft-model", default="test-tiny",
                    help="--spec model: preset whose architecture sizes "
                         "the draft model (vocab/block/dtype forced to "
                         "the target's)")
    ps.add_argument("--prompt-mode", default="random",
                    choices=["random", "repeat", "shared_prefix"],
                    help="'repeat' tiles small patterns (the "
                         "speculative-friendly repetitive trace); "
                         "'shared_prefix' gives every prompt one common "
                         "prefix (the radix-prefix-cache traffic shape)")
    ps.add_argument("--json", action="store_true",
                    help="also print the summary as one JSON line")
    ps.add_argument("--trace-out", default=None,
                    help="write a Perfetto-loadable Chrome trace of the "
                         "replay here: one span tree per request "
                         "(submit -> queue -> admit -> prefill -> "
                         "decode/verify -> finish) on per-slot tracks, "
                         "with prefix-hit/COW/eviction/recovery markers "
                         "(docs/observability.md)")
    ps.add_argument("--metrics-timeline", default=None,
                    help="write a JSONL time series of every engine "
                         "counter/gauge/histogram here (one snapshot per "
                         "--metrics-timeline-interval, plus first/last)")
    ps.add_argument("--metrics-timeline-interval", type=float, default=0.5,
                    help="seconds between metrics-timeline snapshots")
    ps.add_argument("--metrics-out", default=None,
                    help="write the end-of-run metrics as Prometheus "
                         "text exposition here (the /metrics scrape "
                         "format)")
    ps.add_argument("--profile-dir", default=None,
                    help="capture a jax.profiler device trace of a few "
                         "engine steps here (same contract as the train "
                         "subcommand; view in TensorBoard/Perfetto next "
                         "to --trace-out)")
    ps.add_argument("--profile-start", type=int, default=10,
                    help="engine step the device capture opens at")
    ps.add_argument("--profile-steps", type=int, default=5,
                    help="engine steps the device capture covers")
    ps.set_defaults(fn=cmd_serve_replay)

    pv = sub.add_parser("serve",
                        help="run the HTTP/SSE serving fleet: N engine "
                             "replicas behind the prefix-affinity "
                             "router, with submit/stream/cancel/"
                             "healthz/metrics endpoints")
    add_config_flags(pv)
    pv.add_argument("--rng-impl", default=None,
                    choices=["threefry2x32", "rbg"])
    pv.add_argument("--checkpoint-dir", default=None)
    pv.add_argument("--host", default="127.0.0.1")
    pv.add_argument("--port", type=int, default=8000)
    pv.add_argument("--replicas", type=int, default=1,
                    help="engine replicas behind the router")
    pv.add_argument("--journal-dir", default=None,
                    help="in-process mode: per-replica crash journals "
                         "live here (cross-replica requeue after a "
                         "replica death); --multiproc: the LAUNCHER's "
                         "base dir for per-worker PRIVATE dirs "
                         "(worker{i}/journal.jsonl + log) — nothing "
                         "is shared between processes "
                         "(docs/robustness.md)")
    pv.add_argument("--ledger", default=None,
                    help="the ROUTER's own crash journal: submits at "
                         "fleet acceptance, finishes at terminal "
                         "results; a restarted router requeues its "
                         "accepted-but-unfinished set from here — "
                         "recovery that reads NO worker filesystem "
                         "(survives total worker-host loss). Default "
                         "under --multiproc: "
                         "<journal-dir>/router_ledger.jsonl")
    pv.add_argument("--ledger-fsync", action="store_true",
                    help="fsync the router ledger's finish records "
                         "(narrows the torn-tail window to the submit "
                         "side, which only ever re-decodes)")
    pv.add_argument("--no-affinity", action="store_true",
                    help="disable radix-prefix-affinity routing "
                         "(pure least-loaded)")
    pv.add_argument("--wedge-budget-s", type=float, default=0.0,
                    help="per-replica step budget for the router's "
                         "wedge probe (0 = detection off); a replica "
                         "over budget --wedge-patience times in a row "
                         "is quarantined and its in-flight work "
                         "re-routed")
    pv.add_argument("--wedge-patience", type=int, default=2)
    add_engine_flags(pv)
    pv.add_argument("--multiproc", action="store_true",
                    help="run replicas as real worker PROCESSES "
                         "(serve-worker) under the process supervisor: "
                         "supervised restarts with backoff, rolling "
                         "restarts, SIGKILL-survivable exactly-once "
                         "streams; requires --journal-dir")
    pv.add_argument("--restart-budget", type=int, default=3,
                    help="--multiproc: crash restarts per worker before "
                         "quarantine (in-flight work requeued onto "
                         "survivors from the router's ledger)")
    pv.add_argument("--autoscale-max", type=int, default=0,
                    help="--multiproc: enable the autoscaler with this "
                         "many workers as the ceiling (0 = fixed "
                         "fleet). --replicas is the STARTING size; "
                         "sustained backlog spawns workers up to the "
                         "ceiling, sustained lull drains them down to "
                         "--autoscale-min through the rolling-restart "
                         "drain path (zero dropped requests)")
    pv.add_argument("--autoscale-min", type=int, default=1,
                    help="--multiproc autoscaler floor")
    pv.add_argument("--listen-host", default="127.0.0.1",
                    help="--multiproc: interface the worker "
                         "registration listener binds (default "
                         "loopback — the zero-egress posture; "
                         "0.0.0.0 accepts `serve-worker "
                         "--router-addr` registrations from other "
                         "hosts)")
    pv.add_argument("--step-timeout-s", type=float, default=10.0,
                    help="--multiproc: RPC budget for one worker step; "
                         "a hung (SIGSTOPped) worker costs the router "
                         "at most this per step")
    pv.add_argument("--no-fsync", action="store_true",
                    help="--multiproc: disable the workers' "
                         "fsync-per-finish journal durability")
    pv.add_argument("--idle-timeout-s", type=float, default=30.0,
                    help="drop a connection that stalls mid-headers/"
                         "body or stops consuming its SSE stream for "
                         "this long (slow-loris guard; 0 = off)")
    pv.add_argument("--rate-limit-rps", type=float, default=0.0,
                    help="per-client submit rate (token bucket keyed "
                         "on the x-client-id header; over-rate submits "
                         "get 429 + Retry-After; 0 = off)")
    pv.add_argument("--rate-limit-burst", type=float, default=10.0,
                    help="token-bucket capacity: submits a quiet "
                         "client may burst before the sustained rate "
                         "applies")
    pv.add_argument("--trace-out", default=None,
                    help="write a Perfetto trace (router + per-replica "
                         "tracks) at shutdown")
    pv.add_argument("--trace-jsonl", default=None,
                    help="stream trace events to this JSONL sink as "
                         "they happen (crash-tolerant)")
    pv.set_defaults(fn=cmd_serve)

    pw = sub.add_parser("serve-worker",
                        help="one fleet worker process: an engine "
                             "behind the serve/rpc.py socket protocol "
                             "with a locked crash journal and startup "
                             "journal replay (spawned by `serve "
                             "--multiproc` / the process supervisor; "
                             "runnable by hand for debugging)")
    add_config_flags(pw)
    pw.add_argument("--rng-impl", default=None,
                    choices=["threefry2x32", "rbg"])
    pw.add_argument("--checkpoint-dir", default=None)
    pw.add_argument("--host", default="127.0.0.1")
    pw.add_argument("--port", type=int, default=0,
                    help="RPC port (0 = ephemeral; the bound port is "
                         "announced in the --router-addr register "
                         "frame and the stderr banner)")
    pw.add_argument("--journal", default=None,
                    help="crash journal path (exclusively flock-ed; "
                         "replayed at startup; WORKER-LOCAL — the "
                         "router reconciles over the journal_drain "
                         "RPC, never this file)")
    pw.add_argument("--router-addr", default=None,
                    help="host:port of the fleet's registration "
                         "listener: once warmed + replayed + bound, "
                         "the worker announces itself there with one "
                         "register frame (port/pid/gen/replayed + "
                         "protocol version + engine shape hash) and "
                         "becomes routable — the no-shared-filesystem "
                         "handshake; run a worker on ANY host that "
                         "can reach this address. A protocol/shape "
                         "mismatch exits 3 (RpcProtocolError)")
    pw.add_argument("--worker-idx", type=int, default=-1,
                    help="supervisor-managed replica index (-1 = "
                         "unmanaged: register as a brand-new replica "
                         "and grow the fleet)")
    pw.add_argument("--gen", type=int, default=0,
                    help="spawn generation (carried in the register "
                         "frame so the supervisor never attaches a "
                         "stale incarnation)")
    pw.add_argument("--no-fsync", action="store_true",
                    help="disable fsync-per-finish journal durability")
    pw.add_argument("--tier", default="mixed",
                    choices=["mixed", "prefill", "decode"],
                    help="disaggregation role (serve/disagg.py), "
                         "advertised at registration: 'prefill' "
                         "workers take only prefill_only prompt work "
                         "and export finished KV pages, 'decode' "
                         "workers receive pages and own the streams, "
                         "'mixed' (default) does both — the colocated "
                         "fleet")
    pw.add_argument("--reregister-idle-s", type=float, default=5.0,
                    help="router-silence threshold before this worker "
                         "re-sends its register frame (bounded "
                         "exponential backoff): a RESTARTED router's "
                         "fresh listener re-attaches the worker "
                         "without operator action — registration is "
                         "no longer once-at-startup")
    add_engine_flags(pw)
    pw.set_defaults(fn=cmd_serve_worker)

    pe = sub.add_parser("eval", help="estimate train/val loss")
    add_config_flags(pe)
    pe.add_argument("--rng-impl", default=None,
                    choices=["threefry2x32", "rbg"],
                    help="must match the checkpoint's training run")
    pe.add_argument("--checkpoint-dir", default=None)
    pe.set_defaults(fn=cmd_eval)

    pl = sub.add_parser("lint",
                        help="graftlint: JAX-hazard static analysis "
                             "(recompiles, host syncs, RNG reuse, "
                             "dynamic_update_slice clamps, ...) — "
                             "CPU-only, no jax import, tier-1 fast")
    from .analysis.cli import add_lint_flags, run_lint
    add_lint_flags(pl)
    pl.set_defaults(fn=run_lint)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
