"""replicatinggpt_tpu — a TPU-native GPT training/inference framework.

A ground-up JAX/XLA/Pallas/pjit re-design with the capabilities of
ChaitIITB/ReplicatingGPT (see SURVEY.md): char/BPE tokenization, GPT-1/GPT-2
style decoder-only transformers, AdamW training with periodic eval,
KV-cached autoregressive sampling, checkpoint save/resume, HF GPT-2 weight
import — plus the TPU-native scaling layer the reference lacks: mesh-sharded
DP/FSDP/TP/SP execution via XLA collectives, flash attention in Pallas, and
ring attention for long context.
"""

__version__ = "0.1.0"

from .config import Config, MeshConfig, ModelConfig, TrainConfig, get_config

__all__ = ["Config", "ModelConfig", "TrainConfig", "MeshConfig",
           "get_config", "__version__"]
