"""Weight quantization: absmax-per-output-channel int8/fp8 inference
with dequant fused into the matmuls, behind a calibration pass.

The four block kernels (qkv / attn_out / mlp_up / mlp_down) carry
~all of a decode step's parameter bytes — the stream the fused decode
roofline showed the step is bound by. Each is quantized symmetrically
per OUTPUT channel: ``scale[c] = absmax(W[:, c]) / qmax``, stored as a
``<name>_scale`` float32 vector next to the int8/fp8 kernel in the
params pytree. Per-output-channel scales commute through the matmul,
so dequant is ``(x @ Wq) * scale`` — one multiply on the tiny output
row, fused by XLA into the matmul's epilogue; the full-precision
weight is never rematerialized (models.gpt._wmm is the one consumer).

Embeddings, positional table, layernorms, biases and the LM head stay
at their original precision: they are a rounding error of the byte
stream and the head's logit precision is the product's accuracy.

Calibration (``calibrate``): scales themselves are data-free (weight
absmax), but the PASS runs a short token trace through the quantized
and unquantized models and measures the logit divergence the chosen
dtype actually costs — the artifact serialized next to the checkpoint
(``save_calibration``: scales as .npz + a JSON report with the
measured max/mean |Δlogit| against the pinned budget in
quant.DIVERGENCE_BUDGET). A reloaded engine applies the SERIALIZED
scales (``load_calibration`` + ``quantize_params(scales=...)``), so
the served model is bit-identical to the calibrated artifact.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: the block kernels quantized for inference (everything else keeps
#: its original dtype — see module docstring)
QUANT_KERNELS = ("qkv_kernel", "attn_out_kernel", "mlp_up_kernel",
                 "mlp_down_kernel")
SCALES_FILE = "quant_scales.npz"
REPORT_FILE = "quant_calib.json"


def _qmax(weight_dtype: str) -> float:
    return {"int8": 127.0, "fp8": 448.0}[weight_dtype]


def params_are_quantized(params) -> bool:
    # probe the QUANT_KERNELS scale keys specifically: the layernorm
    # gains (ln1_scale/ln2_scale) are ordinary params that merely end
    # in "_scale"
    blocks = params.get("blocks", {})
    return any(name + "_scale" in blocks for name in QUANT_KERNELS)


def weight_scales(params, weight_dtype: str) -> Dict[str, jnp.ndarray]:
    """Absmax-per-output-channel scales for every QUANT_KERNELS entry:
    kernel (L, Cin, Cout) -> scale (L, Cout) float32."""
    qmax = _qmax(weight_dtype)
    out = {}
    for name in QUANT_KERNELS:
        w = params["blocks"][name].astype(jnp.float32)
        out[name] = jnp.maximum(jnp.max(jnp.abs(w), axis=1) / qmax,
                                1e-8)
    return out


def quantize_params(params, weight_dtype: str,
                    scales: Optional[Dict[str, jnp.ndarray]] = None):
    """Return a params pytree with QUANT_KERNELS stored in
    ``weight_dtype`` plus ``<name>_scale`` f32 vectors. ``scales``
    applies a serialized calibration verbatim (bit-identical reload);
    None computes fresh absmax scales."""
    if weight_dtype == "none" or params_are_quantized(params):
        return params
    if scales is None:
        scales = weight_scales(params, weight_dtype)
    qmax = _qmax(weight_dtype)
    blocks = dict(params["blocks"])
    for name in QUANT_KERNELS:
        w = blocks[name].astype(jnp.float32)
        s = jnp.asarray(scales[name], jnp.float32)
        q = w / s[:, None, :]
        if weight_dtype == "int8":
            q = jnp.clip(jnp.round(q), -qmax, qmax).astype(jnp.int8)
        else:
            q = jnp.clip(q, -qmax, qmax).astype(jnp.float8_e4m3fn)
        blocks[name] = q
        blocks[name + "_scale"] = s
    out = dict(params)
    out["blocks"] = blocks
    return out


def calibrate(params, cfg, weight_dtype: str,
              calib_tokens: Optional[np.ndarray] = None,
              seed: int = 0) -> Tuple[dict, dict]:
    """The calibration pass: quantize, then measure what it costs.

    ``calib_tokens`` is a (B, T) int32 token trace (None = a seeded
    synthetic trace over the model's vocab — the zero-egress default).
    Returns ``(quantized_params, report)``; the report carries the
    scales' summary stats and the measured logit divergence on the
    trace, ready for :func:`save_calibration`."""
    from ..models.gpt import forward
    if calib_tokens is None:
        rng = np.random.default_rng(seed)
        T = min(cfg.block_size, 64)
        calib_tokens = rng.integers(0, cfg.vocab_size, (4, T),
                                    dtype=np.int64).astype(np.int32)
    scales = weight_scales(params, weight_dtype)
    qparams = quantize_params(params, weight_dtype, scales=scales)
    toks = jnp.asarray(calib_tokens)
    ref, _ = forward(params, toks, cfg)
    got, _ = forward(qparams, toks, cfg)
    # ONE host fetch of the divergence stats (calibration is offline)
    diff = np.asarray(jnp.abs(got - ref))
    report = {
        "weight_dtype": weight_dtype,
        "kernels": list(QUANT_KERNELS),
        "calib_shape": list(calib_tokens.shape),
        "max_logit_div": float(diff.max()),
        "mean_logit_div": float(diff.mean()),
        "scale_stats": {
            name: {"min": float(np.asarray(s).min()),
                   "max": float(np.asarray(s).max())}
            for name, s in scales.items()},
    }
    return qparams, report


def save_calibration(dir_path: str, params_or_scales, report: dict
                     ) -> Tuple[str, str]:
    """Serialize the calibration next to a checkpoint: the per-channel
    scales as ``quant_scales.npz`` and the report (divergence measured
    on the calibration trace, dtype, kernel list) as
    ``quant_calib.json``. Accepts quantized params (scales extracted)
    or a bare scales dict."""
    os.makedirs(dir_path, exist_ok=True)
    blocks = params_or_scales.get("blocks", params_or_scales)
    scales = {name: np.asarray(blocks[name + "_scale"]
                               if name + "_scale" in blocks
                               else blocks[name])
              for name in QUANT_KERNELS}
    npz = os.path.join(dir_path, SCALES_FILE)
    # atomic tmp+rename on BOTH files (the checkpoint manifest
    # discipline): fleet workers sharing a checkpoint dir may race
    # through prepare_params at startup, and a reader must only ever
    # see a complete artifact or none. pid-suffixed tmp so concurrent
    # writers never clobber each other's half-written file.
    tmp = f"{npz}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **scales)
    os.replace(tmp, npz)
    rep = os.path.join(dir_path, REPORT_FILE)
    tmp = f"{rep}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    os.replace(tmp, rep)
    return npz, rep


def prepare_params(params, cfg, weight_dtype: str,
                   checkpoint_dir: Optional[str] = None, log=None):
    """The CLI-side calibration workflow (serve-replay / serve /
    serve-worker): apply a calibration serialized next to the
    checkpoint when one matches ``weight_dtype`` (bit-identical
    reload), otherwise run :func:`calibrate` now and serialize the
    scales + divergence report for the next start. Engines also
    self-quantize (data-free) when handed unquantized params, so this
    helper is about the durable artifact, not correctness."""
    if weight_dtype == "none" or params_are_quantized(params):
        return params
    if checkpoint_dir:
        scales, report = load_calibration(checkpoint_dir)
        if scales is not None \
                and report.get("weight_dtype") == weight_dtype:
            if log is not None:
                log(f"weight quant: applying serialized {weight_dtype} "
                    f"calibration from {checkpoint_dir} (max logit "
                    f"div {report.get('max_logit_div', 0.0):.4g})")
            return quantize_params(
                params, weight_dtype,
                scales={k: jnp.asarray(v) for k, v in scales.items()})
    qparams, report = calibrate(params, cfg, weight_dtype)
    if log is not None:
        log(f"weight quant: calibrated {weight_dtype} "
            f"(max logit div {report['max_logit_div']:.4g} on the "
            f"calibration trace)")
    if checkpoint_dir:
        try:
            save_calibration(checkpoint_dir, qparams, report)
        except OSError as e:
            if log is not None:
                log(f"weight quant: could not serialize calibration "
                    f"({e}); serving the in-memory quantization")
    return qparams


def load_calibration(dir_path: str):
    """``(scales, report)`` of a serialized calibration, or
    ``(None, None)`` when the directory holds none — including a
    corrupt/truncated artifact (a crashed writer predating the atomic
    rename, a torn disk): the caller recalibrates instead of a worker
    dying at startup on BadZipFile."""
    import zipfile
    npz = os.path.join(dir_path, SCALES_FILE)
    rep = os.path.join(dir_path, REPORT_FILE)
    if not os.path.exists(npz):
        return None, None
    try:
        with np.load(npz) as z:
            scales = {name: z[name] for name in z.files}
        report = {}
        if os.path.exists(rep):
            with open(rep) as f:
                report = json.load(f)
    except (OSError, ValueError, KeyError, zipfile.BadZipFile,
            json.JSONDecodeError):
        return None, None
    return scales, report
