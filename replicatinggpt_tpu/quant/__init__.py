"""Quantization subsystem: int8/fp8 paged KV storage and weight
inference for the serving engine.

Page count is the engine's admission currency — every admitted request
reserves whole pages for its lifetime (serve/pages.py), so bytes per
page directly caps concurrent users per chip. Storing K/V pages in
int8 (or fp8 e4m3) with small per-row scale metadata roughly HALVES
bytes/page vs bf16, which at fixed HBM roughly doubles ``n_pages`` and
therefore doubles admission capacity without touching the scheduler.
Weight-side, int8/fp8 kernels with per-output-channel scales halve the
parameter stream the decode step is bound by and feed the MXU its
native low-precision matmuls.

Two halves, one config:

- :mod:`~.kv` — quantize-on-write / dequant-on-gather for the paged KV
  pool. Scale metadata rides the pool dict as ``ks``/``vs`` arrays
  indexed by the SAME (layer, physical page, page offset) coordinates
  as the K/V writes, so scales flow through copy-on-write splits, LRU
  eviction and radix prefix hits with zero extra bookkeeping — a page
  IS its rows plus their scales. Dequant happens inside the paged
  Pallas kernels (ops/paged_pallas.py, ops/decode_pallas.py) and in
  the XLA gather fallback (models.gpt._gather_pages), so every decode
  route reads quantized pages natively.
- :mod:`~.weights` — absmax-per-output-channel weight quantization
  with dequant FUSED into the matmuls (per-output-channel scales
  commute through ``x @ W``: ``(x @ Wq) * s == x @ (Wq * s)`` up to
  rounding, so the scale lands on the tiny output row, never on a
  rematerialized weight). A calibration pass over a short trace
  measures the resulting logit divergence and serializes scales +
  budget next to the checkpoint.

Threading: :class:`QuantConfig` hangs off ``EngineConfig``
(``kv_quant`` / ``weight_quant`` / ``quant_granularity``, the
``--kv-quant``/``--weight-quant`` CLI knobs), sizes the pool in
``serve/pages.py``, keys the fleet's engine-shape hash
(serve/rpc.py — mismatched quant modes reject at registration), and
carries its own PartitionSpec for the scale arrays on a serving mesh
(parallel.mesh.ServeShardings.scale, page axis over 'data' like the
pool itself).
"""

from __future__ import annotations

from dataclasses import dataclass

#: quantized storage dtypes the subsystem accepts for KV pages and
#: weights ("none" = the unquantized identity)
QUANT_DTYPES = ("none", "int8", "fp8")
#: KV scale granularities: "page" = one f32 scale per written row
#: (page position) shared across the whole model dim — the cheapest
#: metadata that still tracks per-token dynamic range; "head" = one
#: scale per (row, head), tighter for outlier heads at H× the metadata
GRANULARITIES = ("page", "head")

#: pinned logit-divergence budgets vs the unquantized engine (max
#: |Δlogit| over a long greedy trace — measured in tests/test_quant.py
#: at the test-tiny scale with >10x headroom: int8 KV measures ~2e-4,
#: int8 weights ~1.5e-3, fp8 weights ~6e-3 there; the calibration
#: report (quant/weights.py) records the model-specific number next
#: to the checkpoint). Budgets are per quantized HALF: enabling both
#: int8 KV and int8 weights budgets their sum.
DIVERGENCE_BUDGET = {"int8": 0.05, "fp8": 0.2}


@dataclass(frozen=True)
class QuantConfig:
    """What is quantized and how finely the KV scales resolve.

    Hashable + frozen on purpose: the engine threads it (inside
    EngineConfig) next to the static jit arguments, and the fleet's
    shape hash covers it — two workers disagreeing on any field are
    different engines.
    """

    kv_dtype: str = "none"        # paged KV page storage
    weight_dtype: str = "none"    # block matmul kernels
    granularity: str = "page"     # KV scale granularity (page | head)
    act_dtype: str = "none"       # W8A8: activation rows into int8
                                  # weight matmuls (int8 only; requires
                                  # weight_dtype == "int8")

    def validate(self) -> None:
        if self.kv_dtype not in QUANT_DTYPES:
            raise ValueError(f"kv_dtype must be one of {QUANT_DTYPES}, "
                             f"got {self.kv_dtype!r}")
        if self.weight_dtype not in QUANT_DTYPES:
            raise ValueError(f"weight_dtype must be one of "
                             f"{QUANT_DTYPES}, got {self.weight_dtype!r}")
        if self.granularity not in GRANULARITIES:
            raise ValueError(f"granularity must be one of "
                             f"{GRANULARITIES}, got {self.granularity!r}")
        if self.act_dtype not in ("none", "int8"):
            raise ValueError(f"act_dtype must be 'none' or 'int8', "
                             f"got {self.act_dtype!r}")
        if self.act_dtype == "int8" and self.weight_dtype != "int8":
            raise ValueError(
                "act_dtype='int8' (W8A8) requires weight_dtype='int8' — "
                "activation quantization feeds the int8 weight matmuls")

    @property
    def kv_enabled(self) -> bool:
        return self.kv_dtype != "none"

    @property
    def weight_enabled(self) -> bool:
        return self.weight_dtype != "none"

    @property
    def act_enabled(self) -> bool:
        return self.act_dtype != "none"

    @property
    def enabled(self) -> bool:
        return self.kv_enabled or self.weight_enabled or self.act_enabled


from .kv import (dequant_gathered, kv_itemsize, kv_qmax,  # noqa: E402
                 kv_store_dtype, pool_quant_mode, quantize_rows,
                 scale_bytes_per_token)
from .weights import (calibrate, load_calibration,  # noqa: E402
                      params_are_quantized, quantize_params,
                      save_calibration)

__all__ = [
    "QUANT_DTYPES", "GRANULARITIES", "DIVERGENCE_BUDGET", "QuantConfig",
    "kv_store_dtype", "kv_qmax", "kv_itemsize", "quantize_rows",
    "dequant_gathered", "pool_quant_mode", "scale_bytes_per_token",
    "quantize_params", "params_are_quantized", "calibrate",
    "save_calibration", "load_calibration",
]
