"""KV page quantization: quantize-on-write, dequant-on-gather.

The paged pool's write sites (decode/prefill/verify scatters in
models/gpt.py) hand each fresh K/V row here and scatter the returned
(quantized row, scale) pair at the SAME (layer, physical page, offset)
coordinates — scales are just two more pool arrays (``ks``/``vs``)
riding the cache dict, so COW page copies, LRU eviction and radix
prefix sharing carry them for free. Gathers dequant right after the
page gather (``dequant_gathered``), and the paged Pallas kernels do
the same multiply inside their accumulation loops.

Numerics contract (what the parity tests pin): quantization math runs
in float32 regardless of the compute dtype — ``scale = max(amax/qmax,
eps)``, ``q = clip(round(x/scale))`` for int8 or a saturating e4m3
cast for fp8 — and dequant is ``q * scale`` cast back to the compute
dtype. Every route (XLA gather, per-layer kernel, fused kernel) uses
exactly this formula, so kernel-vs-XLA greedy streams stay
token-identical (the in-kernel fake-quant of the fresh column in
ops/decode_pallas.py reproduces it bit-for-bit at f32).
"""

from __future__ import annotations

import jax.numpy as jnp

#: floor on a row's scale: an all-zero row (pool init, padding) must
#: dequant to exactly zero, never divide by zero
SCALE_EPS = 1e-8


def kv_store_dtype(kv_dtype: str):
    """Storage dtype of a quantized pool's K/V arrays."""
    return {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}[kv_dtype]


def kv_qmax(kv_dtype: str) -> float:
    """Largest magnitude the storage dtype represents (int8 symmetric
    127; fp8 e4m3 448)."""
    return {"int8": 127.0, "fp8": 448.0}[kv_dtype]


def kv_itemsize(kv_dtype: str, cfg=None) -> int:
    """Bytes per stored K/V element ("none" = the compute dtype's)."""
    if kv_dtype == "none":
        return jnp.dtype({"float32": jnp.float32,
                          "bfloat16": jnp.bfloat16,
                          "float16": jnp.float16}[cfg.dtype]).itemsize \
            if cfg is not None else 2
    return 1


def scale_bytes_per_token(kv_dtype: str, granularity: str,
                          n_head: int) -> int:
    """Scale metadata bytes per token position per layer (K + V):
    2 x f32 at page granularity, 2 x H x f32 at head granularity."""
    if kv_dtype == "none":
        return 0
    return 2 * 4 * (n_head if granularity == "head" else 1)


def pool_quant_mode(cache) -> tuple:
    """(kv_dtype, granularity) of a paged pool, derived from the
    arrays themselves — dtypes and ranks are static under jit, so the
    paged programs never need the config threaded through their traced
    signatures. ``(None, None)`` for an unquantized pool."""
    if "ks" not in cache:
        return None, None
    kv_dtype = "int8" if cache["k"].dtype == jnp.int8 else "fp8"
    # packed pool (L,N,psz,C) / heads pool (L,N,H,psz,D); page-gran
    # scales are (L,N,psz) either way, head-gran adds the H axis
    gran = "head" if cache["ks"].ndim == 4 else "page"
    return kv_dtype, gran


def init_scales(cfg, n_pages: int, page_size: int, granularity: str):
    """Zero-initialized scale arrays for a fresh pool (an unwritten
    row dequants to exactly zero — the same harmless-stale-state
    contract the unquantized pool relies on)."""
    if granularity == "head":
        if cfg.decode_cache_layout == "packed":
            shape = (cfg.n_layer, n_pages, page_size, cfg.n_head)
        else:
            shape = (cfg.n_layer, n_pages, cfg.n_head, page_size)
    else:
        shape = (cfg.n_layer, n_pages, page_size)
    # two DISTINCT arrays: the engine donates the whole pool dict, and
    # XLA rejects the same buffer donated twice
    return {"ks": jnp.zeros(shape, jnp.float32),
            "vs": jnp.zeros(shape, jnp.float32)}


def quantize_rows(rows: jnp.ndarray, kv_dtype: str, n_head: int,
                  granularity: str):
    """Quantize merged K or V rows (..., C) for a pool write.

    Returns ``(q, scale)``: ``q`` (..., C) in the storage dtype and
    ``scale`` (...,) float32 at page granularity or (..., H) at head
    granularity. Math in f32 (see module docstring); an all-zero row
    gets ``SCALE_EPS`` and round-trips to exact zero."""
    qmax = kv_qmax(kv_dtype)
    f = rows.astype(jnp.float32)
    if granularity == "head":
        fh = f.reshape(f.shape[:-1] + (n_head, f.shape[-1] // n_head))
        scale = jnp.maximum(jnp.max(jnp.abs(fh), axis=-1) / qmax,
                            SCALE_EPS)                     # (..., H)
        q = (fh / scale[..., None]).reshape(f.shape)
    else:
        scale = jnp.maximum(jnp.max(jnp.abs(f), axis=-1) / qmax,
                            SCALE_EPS)                     # (...,)
        q = f / scale[..., None]
    if kv_dtype == "int8":
        q = jnp.clip(jnp.round(q), -qmax, qmax).astype(jnp.int8)
    else:
        q = jnp.clip(q, -qmax, qmax).astype(jnp.float8_e4m3fn)
    return q, scale


def fake_quantize_rows(rows: jnp.ndarray, kv_dtype: str, n_head: int,
                       granularity: str) -> jnp.ndarray:
    """quantize -> dequantize in one step (f32 out): what a fresh row
    is WORTH once it lands in the pool. The kernel routes attend this
    for the fresh column so write-then-attend equivalence survives
    quantization (the stored row dequants to exactly this value)."""
    q, scale = quantize_rows(rows, kv_dtype, n_head, granularity)
    if granularity == "head":
        qh = q.astype(jnp.float32).reshape(
            q.shape[:-1] + (n_head, q.shape[-1] // n_head))
        return (qh * scale[..., None]).reshape(q.shape)
    return q.astype(jnp.float32) * scale[..., None]


def fake_quantize_row_f32(row: jnp.ndarray, qmax: float,
                          eps: float = SCALE_EPS) -> jnp.ndarray:
    """quantize -> dequantize ONE row in pure f32 — the Pallas-kernel-
    body form of :func:`fake_quantize_rows` at page granularity (the
    fused decode kernel fake-quantizes its fresh column in-kernel and
    cannot cheaply materialize int8 there). Quantized values are
    integers within ±qmax, exact in f32, so skipping the int cast is
    value-identical to the batched helper — pinned against it in
    tests/test_quant.py; change the math HERE and both routes move
    together."""
    f = row.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(f)) / qmax, eps)
    return jnp.clip(jnp.round(f / s), -qmax, qmax) * s


def _fake_quantize_span_f32(f: jnp.ndarray, kv_dtype: str,
                            eps: float = SCALE_EPS) -> jnp.ndarray:
    """One scale span (a whole row at page granularity, one head's
    lanes at head granularity) through quantize -> dequantize in f32.
    int8 values are integers within ±qmax — exact in f32, so the int
    cast is skipped (value-identical, pinned in tests/test_quant.py);
    fp8 keeps the ACTUAL saturating e4m3 cast round-trip, because e4m3
    mantissa rounding is not representable as a round()/clip() in f32.
    """
    qmax = kv_qmax(kv_dtype)
    s = jnp.maximum(jnp.max(jnp.abs(f)) / qmax, eps)
    if kv_dtype == "int8":
        return jnp.clip(jnp.round(f / s), -qmax, qmax) * s
    q = jnp.clip(f / s, -qmax, qmax).astype(jnp.float8_e4m3fn)
    return q.astype(jnp.float32) * s


def fake_quantize_row_body(row: jnp.ndarray, kv_dtype: str, n_head: int,
                           granularity: str,
                           eps: float = SCALE_EPS) -> jnp.ndarray:
    """Kernel-body form of :func:`fake_quantize_rows` for ONE (1, C)
    row, any dtype x granularity — what the fused decode kernel applies
    to its fresh K/V column in-kernel so the column attends exactly the
    value the caller's quantize-on-write scatter will store. Head
    granularity runs the span math per static head lane slice (the
    kernels address heads as D-wide lane slices, so the python loop
    unrolls to the same slices). Math is :func:`quantize_rows`'s at
    f32 — pinned value-identical in tests/test_quant.py; change it
    THERE and HERE together."""
    f = row.astype(jnp.float32)
    if granularity == "head":
        D = f.shape[-1] // n_head
        return jnp.concatenate(
            [_fake_quantize_span_f32(f[:, i * D:(i + 1) * D], kv_dtype,
                                     eps)
             for i in range(n_head)], axis=-1)
    return _fake_quantize_span_f32(f, kv_dtype, eps)


def dequant_gathered(g: jnp.ndarray, s: jnp.ndarray, packed: bool,
                     n_head: int, cd) -> jnp.ndarray:
    """Dequantize a page-gathered view back to the compute dtype.

    ``g``: (B, mp, psz, C) packed or (B, mp, H, psz, D) heads layout,
    fresh off ``pool[tables]``; ``s``: the same-gathered scales —
    (B, mp, psz) page granularity, or head granularity's
    (B, mp, psz, H) packed / (B, mp, H, psz) heads."""
    gf = g.astype(jnp.float32)
    if packed:
        if s.ndim == 4:     # head granularity: per (row, head) scale
            B, mp, psz, C = g.shape
            gh = gf.reshape(B, mp, psz, n_head, C // n_head)
            gf = (gh * s[..., None]).reshape(B, mp, psz, C)
        else:
            gf = gf * s[..., None]
    else:
        if s.ndim == 4:     # (B, mp, H, psz)
            gf = gf * s[..., None]
        else:               # (B, mp, psz): broadcast over H and D
            gf = gf * s[:, :, None, :, None]
    return gf.astype(cd)
