"""Pipeline parallelism: the block stack sharded over a 'pipe' mesh axis,
microbatches flowing stage-to-stage via ``lax.ppermute`` (GPipe-style
skewed schedule).

The reference runs its blocks in an in-process Python loop on one device
(GPT-2.py:117-118); SURVEY.md §2.1 lists PP as the remaining parallelism
row. TPU-native formulation: each of the P stages holds n_layer/P of the
layer-stacked block params (the (L, ...) leading dim is sharded over
'pipe' — see mesh.py partition rules), the global batch splits into M
microbatches, and the schedule runs M + P - 1 ticks. At tick t, stage s
works on microbatch m = t - s (stage 0 reads fresh microbatches, the last
stage banks finished ones), then every stage hands its activation to stage
s+1 over a neighbor ppermute riding ICI. Finished outputs are broadcast
from the last stage with a masked psum. The whole schedule is a
``lax.scan``, so reverse-mode AD gives GPipe's backward for free.

Composition: inside the shard_map region the 'seq' axis name is in scope,
so the per-block attention core is the ring-attention local body — seq
parallelism composes with PP natively (a 1-sized seq axis degrades to the
plain causal core). The 'data' axis partitions microbatch rows as usual.
The 'model' axis is *replicated* through this region in the current
implementation (kernels are all-gathered on entry; TP-inside-PP would need
hand-written Megatron collectives here — future work, documented
limitation).

Bubble math: utilization = M / (M + P - 1); pick microbatches >= 4*P to
keep the bubble under ~25%.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..config import MeshConfig, ModelConfig


def _pp_local(x: jnp.ndarray, blocks: Dict[str, jnp.ndarray],
              rng: Optional[jax.Array], *, cfg: ModelConfig, train: bool,
              n_stages: int, axis_name: str = "pipe") -> jnp.ndarray:
    """Per-device pipeline schedule.

    x: (M, Bm, T_local, C) — all microbatches (replicated over 'pipe';
    only stage 0 reads them). blocks: local leaves with leading
    n_layer/n_stages. Returns (M, Bm, T_local, C) finished activations
    (identical on every stage after the final broadcast).
    """
    from ..models.gpt import _block
    from .ring_attention import _ring_local

    stage = jax.lax.axis_index(axis_name)
    M = x.shape[0]
    Lp = cfg.n_layer // n_stages
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    attn_local = functools.partial(_ring_local, axis_name="seq", scale=None)

    if rng is not None:
        # the rng enters replicated; decorrelate dropout masks across the
        # data/seq shards (each device draws masks over its *local* shape,
        # so an unfolded key would repeat the same mask on every shard)
        shard_id = (jax.lax.axis_index("data") * jax.lax.axis_size("seq")
                    + jax.lax.axis_index("seq"))
        rng = jax.random.fold_in(rng, shard_id)

    def run_stage(h: jnp.ndarray, m_idx: jnp.ndarray) -> jnp.ndarray:
        """One microbatch through this stage's local layers."""
        def body(carry, inputs):
            lp, l_local = inputs
            r = None
            if rng is not None:
                g_layer = stage * Lp + l_local
                r = jax.random.fold_in(jax.random.fold_in(rng, g_layer),
                                       m_idx)
            return _block(carry, lp, cfg, rng=r, train=train,
                          attention_fn=attn_local), None

        h, _ = jax.lax.scan(body, h, (blocks, jnp.arange(Lp)))
        return h

    def tick(carry, t):
        buf, out = carry
        m = t - stage                       # microbatch this stage handles
        active = jnp.logical_and(m >= 0, m < M)
        m_c = jnp.clip(m, 0, M - 1)
        # stage 0 ingests a fresh microbatch; later stages consume what
        # arrived over the ring last tick (zeros during fill — harmless)
        inp = jnp.where(stage == 0, x[jnp.clip(t, 0, M - 1)], buf)
        h = run_stage(inp, m_c)
        banked = jax.lax.dynamic_update_index_in_dim(out, h, m_c, 0)
        out = jnp.where(jnp.logical_and(stage == n_stages - 1, active),
                        banked, out)
        buf = jax.lax.ppermute(h, axis_name, perm)
        return (buf, out), None

    buf0 = jnp.zeros_like(x[0])
    out0 = jnp.zeros_like(x)
    (_, out), _ = jax.lax.scan(tick, (buf0, out0),
                               jnp.arange(M + n_stages - 1))
    # everyone needs the result (loss/head are replicated over 'pipe'):
    # masked psum broadcasts the last stage's bank
    out = jax.lax.psum(
        jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out)),
        axis_name)
    return out


def pipeline_blocks(x: jnp.ndarray, blocks, cfg: ModelConfig, *,
                    mesh: Mesh, n_microbatches: int,
                    rng: Optional[jax.Array] = None,
                    train: bool = False) -> jnp.ndarray:
    """Run the block stack pipelined. x: global (B, T, C); blocks: the
    layer-stacked params dict ((L, ...) leaves, 'pipe'-sharded on dim 0).

    Drop-in replacement for models.gpt._run_blocks on a pipe>1 mesh.
    """
    B, T, C = x.shape
    M = n_microbatches
    assert B % M == 0, f"batch {B} not divisible into {M} microbatches"
    n_stages = mesh.shape["pipe"]
    assert cfg.n_layer % n_stages == 0, (
        f"n_layer {cfg.n_layer} not divisible by {n_stages} pipeline stages")

    xm = x.reshape(M, B // M, T, C)
    x_spec = P(None, "data", "seq", None)
    blocks_spec = jax.tree_util.tree_map(
        lambda leaf: P(*(("pipe",) + (None,) * (leaf.ndim - 1))), blocks)
    rng_spec = None if rng is None else P()

    fn = jax.shard_map(
        functools.partial(_pp_local, cfg=cfg, train=train,
                          n_stages=n_stages),
        mesh=mesh,
        in_specs=(x_spec, blocks_spec, rng_spec),
        out_specs=x_spec,
        check_vma=False)
    out = fn(xm, blocks, rng)
    return out.reshape(B, T, C)


def make_pipeline_blocks_fn(mesh: Mesh, mesh_cfg: MeshConfig):
    """blocks_fn for ``models.gpt.forward`` — binds mesh + microbatch count
    (mesh_cfg.microbatches, defaulting to 2 per stage)."""
    M = mesh_cfg.microbatches or 2 * mesh_cfg.pipe

    def blocks_fn(x, blocks, cfg, *, rng, train):
        return pipeline_blocks(x, blocks, cfg, mesh=mesh, n_microbatches=M,
                               rng=rng, train=train)

    return blocks_fn
