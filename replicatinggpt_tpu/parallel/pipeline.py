"""Pipeline parallelism: the block stack sharded over a 'pipe' mesh axis,
microbatches flowing stage-to-stage via ``lax.ppermute`` (GPipe-style
skewed schedule).

The reference runs its blocks in an in-process Python loop on one device
(GPT-2.py:117-118); SURVEY.md §2.1 lists PP as the remaining parallelism
row. TPU-native formulation: each of the P stages holds n_layer/P of the
layer-stacked block params (the (L, ...) leading dim is sharded over
'pipe' — see mesh.py partition rules), the global batch splits into M
microbatches, and the schedule runs M + P - 1 ticks. At tick t, stage s
works on microbatch m = t - s (stage 0 reads fresh microbatches, the last
stage banks finished ones), then every stage hands its activation to stage
s+1 over a neighbor ppermute riding ICI. Finished outputs are broadcast
from the last stage with a masked psum. The whole schedule is a
``lax.scan``, so reverse-mode AD gives GPipe's backward for free.

Composition: inside the shard_map region the 'seq' axis name is in scope,
so the per-block attention core is the ring-attention local body — seq
parallelism composes with PP natively (a 1-sized seq axis degrades to the
plain causal core). The 'data' axis partitions microbatch rows as usual.
The 'model' axis runs real Megatron TP inside the region (:func:`_block_tp`):
column-parallel QKV/MLP-up, row-parallel attn-out/MLP-down with explicit
``psum`` over 'model', biases added post-reduction. One layout wrinkle: a
contiguous shard of the fused (C, 3C) [q|k|v] kernel's last dim crosses
projection boundaries, so the kernel is reshaped host-side to (L, C, 3, C)
and sharded on the per-projection dim — each device then holds the same
head-slice of q, k, and v (heads stay whole: requires n_head % tp == 0,
else TP falls back to replicated kernels for that run).

Bubble math: utilization = M / (M + P - 1); pick microbatches >= 4*P to
keep the bubble under ~25%.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .compat import axis_size, shard_map

from ..config import MeshConfig, ModelConfig


def _block_tp(x: jnp.ndarray, lp: Dict[str, jnp.ndarray], cfg: ModelConfig,
              *, rng: Optional[jax.Array], train: bool, attention_fn,
              tp_axis: str = "model") -> jnp.ndarray:
    """Megatron tensor-parallel transformer block for shard_map regions.

    Mirrors models.gpt._block, but kernels arrive as raw local shards:
    qkv (C, 3, C/tp) column-parallel (per-projection dim pre-reshaped by
    pipeline_blocks), mlp_up (C, 4C/tp) column-parallel, attn_out (C/tp, C)
    and mlp_down (4C/tp, C) row-parallel with an explicit psum over
    ``tp_axis``. Row-parallel biases are added after the reduction (adding
    per-shard then summing would count them tp times). Activations stay
    replicated over 'model', so dropout masks (same rng on every model
    shard) remain consistent.
    """
    from ..models.gpt import (_activation, _dropout, _layer_norm,
                              _merge_heads, _split_heads)

    cd = x.dtype
    tp = axis_size(tp_axis)
    r_attn, r_drop1, r_drop2 = (jax.random.split(rng, 3)
                                if rng is not None else (None, None, None))
    if r_attn is not None:
        # heads are sharded over 'model' here (unlike the activations,
        # whose dropout keys must agree across model shards) — each head
        # shard needs its own attention-mask stream
        r_attn = jax.random.fold_in(r_attn, jax.lax.axis_index(tp_axis))
    h = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"], cfg.layernorm_eps)
    C = h.shape[-1]
    qkv_k = lp["qkv_kernel"].astype(cd)      # (C, 3, C/tp) local
    qkv_b = lp["qkv_bias"].astype(cd)        # (3, C/tp) local
    qkv = h @ qkv_k.reshape(C, -1) + qkv_b.reshape(-1)
    q, k, v = jnp.split(qkv, 3, axis=-1)     # each (B, T, C/tp)
    q, k, v = (_split_heads(t, cfg.n_head // tp) for t in (q, k, v))
    attn = attention_fn(q, k, v, rng=r_attn, train=train)
    attn = _merge_heads(attn)                # (B, T, C/tp): this shard's heads
    attn = attn @ lp["attn_out_kernel"].astype(cd)        # partial (B, T, C)
    attn = (jax.lax.psum(attn, tp_axis)
            + lp["attn_out_bias"].astype(cd))
    x = x + _dropout(attn, cfg.dropout, r_drop1, train)
    h = _layer_norm(x, lp["ln2_scale"], lp["ln2_bias"], cfg.layernorm_eps)
    h = _activation(h @ lp["mlp_up_kernel"].astype(cd)
                    + lp["mlp_up_bias"].astype(cd), cfg.activation)
    h = h @ lp["mlp_down_kernel"].astype(cd)              # partial (B, T, C)
    h = jax.lax.psum(h, tp_axis) + lp["mlp_down_bias"].astype(cd)
    return x + _dropout(h, cfg.dropout, r_drop2, train)


def _pp_local(x: jnp.ndarray, blocks: Dict[str, jnp.ndarray],
              rng: Optional[jax.Array], *, cfg: ModelConfig, train: bool,
              n_stages: int, tp_sharded: bool,
              axis_name: str = "pipe") -> jnp.ndarray:
    """Per-device pipeline schedule.

    x: (M, Bm, T_local, C) — all microbatches (replicated over 'pipe';
    only stage 0 reads them). blocks: local leaves with leading
    n_layer/n_stages ('model'-sharded kernels when tp_sharded). Returns
    (M, Bm, T_local, C) finished activations (identical on every stage
    after the final broadcast).
    """
    from ..models.gpt import _block
    from .ring_attention import _ring_local

    stage = jax.lax.axis_index(axis_name)
    M = x.shape[0]
    Lp = cfg.n_layer // n_stages
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    # the in-scope ring core applies attention-weight dropout from the
    # per-layer rng (pre-folded by (data, seq) shard below — the ring
    # folds its own seq/hop/chunk indices, and _block_tp folds 'model')
    attn_local = functools.partial(_ring_local, axis_name="seq", scale=None,
                                   dropout_rate=cfg.attn_dropout)

    if rng is not None:
        # the rng enters replicated; decorrelate dropout masks across the
        # data/seq shards (each device draws masks over its *local* shape,
        # so an unfolded key would repeat the same mask on every shard).
        # NOT folded over 'model': activations are replicated across model
        # shards, so their dropout masks must agree.
        shard_id = (jax.lax.axis_index("data") * axis_size("seq")
                    + jax.lax.axis_index("seq"))
        rng = jax.random.fold_in(rng, shard_id)

    def run_stage(h: jnp.ndarray, m_idx: jnp.ndarray) -> jnp.ndarray:
        """One microbatch through this stage's local layers."""
        def body(carry, inputs):
            lp, l_local = inputs
            r = None
            if rng is not None:
                g_layer = stage * Lp + l_local
                r = jax.random.fold_in(jax.random.fold_in(rng, g_layer),
                                       m_idx)
            if tp_sharded:
                out = _block_tp(carry, lp, cfg, rng=r, train=train,
                                attention_fn=attn_local)
            else:
                out = _block(carry, lp, cfg, rng=r, train=train,
                             attention_fn=attn_local)
            return out, None

        h, _ = jax.lax.scan(body, h, (blocks, jnp.arange(Lp)))
        return h

    def tick(carry, t):
        buf, out = carry
        m = t - stage                       # microbatch this stage handles
        active = jnp.logical_and(m >= 0, m < M)
        m_c = jnp.clip(m, 0, M - 1)
        # stage 0 ingests a fresh microbatch; later stages consume what
        # arrived over the ring last tick (zeros during fill — harmless)
        inp = jnp.where(stage == 0, x[jnp.clip(t, 0, M - 1)], buf)
        h = run_stage(inp, m_c)
        banked = jax.lax.dynamic_update_index_in_dim(out, h, m_c, 0)
        out = jnp.where(jnp.logical_and(stage == n_stages - 1, active),
                        banked, out)
        buf = jax.lax.ppermute(h, axis_name, perm)
        return (buf, out), None

    buf0 = jnp.zeros_like(x[0])
    out0 = jnp.zeros_like(x)
    (_, out), _ = jax.lax.scan(tick, (buf0, out0),
                               jnp.arange(M + n_stages - 1))
    # everyone needs the result (loss/head are replicated over 'pipe'):
    # masked psum broadcasts the last stage's bank
    out = jax.lax.psum(
        jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out)),
        axis_name)
    return out


def pipeline_blocks(x: jnp.ndarray, blocks, cfg: ModelConfig, *,
                    mesh: Mesh, n_microbatches: int,
                    rng: Optional[jax.Array] = None,
                    train: bool = False) -> jnp.ndarray:
    """Run the block stack pipelined. x: global (B, T, C); blocks: the
    layer-stacked params dict ((L, ...) leaves, 'pipe'-sharded on dim 0).

    Drop-in replacement for models.gpt._run_blocks on a pipe>1 mesh.
    """
    B, T, C = x.shape
    M = n_microbatches
    assert B % M == 0, f"batch {B} not divisible into {M} microbatches"
    n_stages = mesh.shape["pipe"]
    assert cfg.n_layer % n_stages == 0, (
        f"n_layer {cfg.n_layer} not divisible by {n_stages} pipeline stages")

    xm = x.reshape(M, B // M, T, C)
    x_spec = P(None, "data", "seq", None)
    tp = mesh.shape.get("model", 1)
    tp_sharded = tp > 1 and cfg.n_head % tp == 0 and cfg.n_embd % tp == 0
    if tp > 1 and not tp_sharded:
        import warnings
        warnings.warn(
            f"pipeline TP disabled: n_head={cfg.n_head}/n_embd={cfg.n_embd} "
            f"not divisible by model axis {tp}; kernels replicate through "
            f"the pipeline region (2x+ HBM per stage, idle model-axis "
            f"devices)")
    if tp_sharded:
        # keep Megatron TP live inside the region: kernels enter sharded
        # over 'model' instead of being all-gathered. The fused [q|k|v]
        # last dim can't be contiguously column-sharded (a 3C/tp slice
        # crosses projection boundaries), so it is reshaped to a
        # per-projection dim first — each shard then holds the same head
        # slice of q, k and v. Known trade-off: the at-rest spec
        # (mesh.py, contiguous 3C shard) differs from this region layout,
        # so XLA reshards the QKV weights across 'model' each step —
        # O(12 d^2/tp) per layer, small next to activations but not free;
        # a per-projection at-rest layout would remove it at the cost of
        # changing the checkpoint/HF-import pytree shape.
        L = blocks["qkv_kernel"].shape[0]
        blocks = dict(blocks)
        blocks["qkv_kernel"] = blocks["qkv_kernel"].reshape(L, C, 3, C)
        blocks["qkv_bias"] = blocks["qkv_bias"].reshape(L, 3, C)
        tp_specs = {
            "qkv_kernel": P("pipe", None, None, "model"),
            "qkv_bias": P("pipe", None, "model"),
            "mlp_up_kernel": P("pipe", None, "model"),
            "mlp_up_bias": P("pipe", "model"),
            "attn_out_kernel": P("pipe", "model", None),
            "mlp_down_kernel": P("pipe", "model", None),
        }
        blocks_spec = {
            name: tp_specs.get(
                name, P(*(("pipe",) + (None,) * (leaf.ndim - 1))))
            for name, leaf in blocks.items()}
    else:
        blocks_spec = jax.tree_util.tree_map(
            lambda leaf: P(*(("pipe",) + (None,) * (leaf.ndim - 1))), blocks)
    rng_spec = None if rng is None else P()

    fn = shard_map(
        functools.partial(_pp_local, cfg=cfg, train=train,
                          n_stages=n_stages, tp_sharded=tp_sharded),
        mesh=mesh,
        in_specs=(x_spec, blocks_spec, rng_spec),
        out_specs=x_spec,
        check_vma=False)
    out = fn(xm, blocks, rng)
    return out.reshape(B, T, C)


def make_pipeline_blocks_fn(mesh: Mesh, mesh_cfg: MeshConfig):
    """blocks_fn for ``models.gpt.forward`` — binds mesh + microbatch count
    (mesh_cfg.microbatches, defaulting to 2 per stage)."""
    M = mesh_cfg.microbatches or 2 * mesh_cfg.pipe

    def blocks_fn(x, blocks, cfg, *, rng, train):
        return pipeline_blocks(x, blocks, cfg, mesh=mesh, n_microbatches=M,
                               rng=rng, train=train)

    return blocks_fn
