"""Device mesh + partition rules: the framework's entire distributed layer.

The reference has no distributed machinery (SURVEY.md §2.1-§2.2). The
TPU-native replacement is declarative: build a ``jax.sharding.Mesh`` over
axes ``('data', 'seq', 'model')``, attach ``NamedSharding``s to the train
state and batches, and let XLA GSPMD insert the collectives (psum for DP
grad reduction, all-gather for FSDP parameter gathering, reduce-scatter /
all-reduce around the Megatron-style column/row-parallel matmuls) over
ICI/DCN. No hand-written transport code exists anywhere in the framework —
that is the point.

Partition rules (Megatron-style TP over 'model', SURVEY.md §2.1 table):

=====================  ==================  ==========================
param                  shape               spec (layer-stacked dim 0)
=====================  ==================  ==========================
wte                    (V, C)              ('model', None) — vocab-parallel
                                           embedding + tied head
lm_head (untied)       (C, V)              (None, 'model')
qkv_kernel             (L, C, 3C)          (None, None, 'model')  column
attn_out_kernel        (L, C, C)           (None, 'model', None)  row
mlp_up_kernel          (L, C, 4C)          (None, None, 'model')  column
mlp_down_kernel        (L, 4C, C)          (None, 'model', None)  row
biases of column ops   (L, K)              (None, 'model')
everything else        —                   replicated
=====================  ==================  ==========================

FSDP (``MeshConfig.fsdp``) additionally shards each param (and its Adam
moments, which inherit specs by tree-path) over 'data' on the largest
still-unsharded divisible dim — ZeRO-3 semantics for free under GSPMD.
Batches are (B, T) sharded ('data', 'seq').
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import MeshConfig, ModelConfig
from .compat import set_mesh

# param-name → (tp_dim or None); dims are indices into the *unstacked* shape
# (block params carry a leading layer dim handled by offset)
_COLUMN_PARALLEL = {"qkv_kernel", "mlp_up_kernel"}
_COLUMN_BIAS = {"qkv_bias", "mlp_up_bias"}
_ROW_PARALLEL = {"attn_out_kernel", "mlp_down_kernel"}


def make_mesh(mesh_cfg: MeshConfig,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = mesh_cfg.n_devices
    assert len(devices) >= n, (
        f"mesh needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(
        mesh_cfg.data, mesh_cfg.seq, mesh_cfg.model, mesh_cfg.pipe)
    return Mesh(arr, mesh_cfg.axis_names)


def batch_pspec() -> P:
    return P("data", "seq")


def make_batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec())


def superbatch_pspec() -> P:
    """(K, B, T) stacked multi-step superbatch: the scan dim replicates,
    batch rows and sequence keep the (data, seq) layout of a single batch —
    so a K-step lax.scan dispatch sees each step's batch sharded exactly
    like the single-step path."""
    return P(None, "data", "seq")


def make_superbatch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, superbatch_pspec())


def _tp_spec(name: str, ndim: int) -> list:
    """Tensor-parallel placement for a leaf called ``name``."""
    spec = [None] * ndim
    if name == "wte":
        spec[0] = "model"
    elif name == "lm_head":
        spec[1] = "model"
    elif name in _COLUMN_PARALLEL:
        spec[ndim - 1] = "model"
    elif name in _COLUMN_BIAS:
        spec[ndim - 1] = "model"
    elif name in _ROW_PARALLEL:
        spec[ndim - 2] = "model"
    return spec


def _leaf_spec(path, shape: Tuple[int, ...], mesh_cfg: MeshConfig) -> P:
    """Spec for one leaf of the train state, identified by its tree path.

    Works uniformly for params and optimizer moments because optax's
    mu/nu subtrees mirror the params dict, so the param name appears as the
    final DictKey on the path either way.
    """
    name = None
    for k in reversed(path):
        if isinstance(k, jax.tree_util.DictKey):
            name = str(k.key)
            break
    in_blocks = any(isinstance(k, jax.tree_util.DictKey)
                    and str(k.key) == "blocks" for k in path)
    ndim = len(shape)
    spec = [None] * ndim
    if name is not None and ndim > 0:
        spec = _tp_spec(name, ndim)
        # drop TP sharding where the dim isn't divisible by the axis size
        for d, ax in enumerate(spec):
            if ax == "model" and shape[d] % mesh_cfg.model != 0:
                spec[d] = None
    # pipeline: each stage stores its slice of the layer-stacked (L, ...) dim
    if (mesh_cfg.pipe > 1 and in_blocks and ndim > 0
            and shape[0] % mesh_cfg.pipe == 0):
        spec[0] = "pipe"
    if mesh_cfg.fsdp and ndim > 0:
        # shard the largest unsharded divisible dim over 'data' (ZeRO-3)
        dims = sorted(range(ndim), key=lambda d: -shape[d])
        for d in dims:
            if spec[d] is None and shape[d] % mesh_cfg.data == 0 \
                    and shape[d] >= mesh_cfg.data:
                spec[d] = "data"
                break
    return P(*spec)


def state_pspecs(tree: Any, mesh_cfg: MeshConfig) -> Any:
    """PartitionSpec pytree for any state-shaped tree (TrainState, params,
    opt_state, ...). Scalars / unnamed leaves replicate."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _leaf_spec(path, tuple(leaf.shape), mesh_cfg),
        tree)


def state_shardings(tree: Any, mesh: Mesh, mesh_cfg: MeshConfig) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), state_pspecs(tree, mesh_cfg))


def param_pspecs(mcfg: ModelConfig, mesh_cfg: MeshConfig) -> Any:
    """Specs for just the model params (used by checkpoint restore and the
    HF importer)."""
    from ..models.gpt import init_params
    abstract = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), mcfg))
    return state_pspecs(abstract, mesh_cfg)


def shard_train_state(create_fn: Callable[[], Any], mesh: Mesh,
                      mesh_cfg: MeshConfig) -> Any:
    """Initialize train state directly in its sharded layout: jit the
    initializer with out_shardings so every device materializes only its own
    parameter/optimizer shards (no host-side full copy)."""
    abstract = jax.eval_shape(create_fn)
    shardings = state_shardings(abstract, mesh, mesh_cfg)
    with set_mesh(mesh):
        return jax.jit(create_fn, out_shardings=shardings)()


# ---------------------------------------------------------------------------
# Serving mesh: the (data, model) layout of the sharded engine
# ---------------------------------------------------------------------------
#
# The serving engine (serve/engine.py) runs on a 2-axis slice of the
# framework mesh: 'data' multiplies KV capacity (the paged pool's
# physical page axis shards across it, so each chip stores
# n_pages/data pages and the same per-chip HBM holds data× more
# aggregate pages), 'model' multiplies attention/MLP FLOPs per step
# (Megatron TP — the same column/row specs training uses, but
# replicated over 'data': FSDP's gather-per-use trades latency for
# memory in exactly the wrong direction for single-token decode).
#
# Page-pool PartitionSpec, designed first (ROADMAP item 1):
#
# =========================  ===========================  ==============
# array                      shape                        spec
# =========================  ===========================  ==============
# page pool (packed)         (L, n_pages, page, C)        (None, 'data',
#                                                          None, 'model')
# page pool (heads)          (L, n_pages, H, page, D)     (None, 'data',
#                                                          'model', None,
#                                                          None)
# step vectors / tables /    (n_slots,) (n_slots, mp)     replicated
# token block                (k, n_slots)
# params                     Megatron TP over 'model'     (see table up
#                            (decode layout: no FSDP)      top)
# =========================  ===========================  ==============
#
# Rationale: the model dim (C, or H for the heads layout) shards over
# 'model' so each chip's page shard stores only its TP heads' K/V —
# the gathered logical view then lines up with the TP-sharded QKV
# activations without resharding. The PAGE axis (not the slot axis)
# shards over 'data': pages are the physical storage (slots are host
# bookkeeping + fixed-shape tables), so page-axis sharding is what
# actually divides HBM bytes per chip. The tiny per-step vectors and
# the (k, n_slots) sampled-token block replicate — the engine fetches
# ONE replicated block per window (`np.asarray` reads a local shard,
# never a cross-device gather), preserving the async engine's
# one-host-snapshot-per-window contract. Non-divisible dims drop their
# axis to None exactly like `_leaf_spec` (documented, not silent: the
# pool's stats() reports the effective mesh shape).


def parse_mesh_shape(text: str) -> Tuple[int, int]:
    """'2x2' / '2,2' / '4x1' -> (data, model). The serving CLI/bench
    flag format; '1x1' is the unsharded identity."""
    s = text.lower().replace(",", "x").split("x")
    if len(s) != 2:
        raise ValueError(f"--mesh-shape must be DxM (e.g. 2x2), got "
                         f"{text!r}")
    d, m = int(s[0]), int(s[1])
    if d < 1 or m < 1:
        raise ValueError(f"--mesh-shape axes must be >= 1, got {text!r}")
    return d, m


def resolve_mesh_shape(text: str, n_devices: int,
                       warn=None) -> Tuple[int, int]:
    """``parse_mesh_shape`` + the device-count downgrade rule — ONE
    definition (message included) for the CLI
    (`engine_config_from_args`) and bench: a mesh the process cannot
    satisfy resolves to (1, 1) (degrade, not die — the
    `_build_mesh_if_needed` convention), with the downgrade reported
    through ``warn`` (a callable taking the message; None = silent)."""
    d, m = parse_mesh_shape(text)
    if d * m > max(n_devices, 1):
        if warn is not None:
            warn(f"serve mesh {text} wants {d * m} devices, have "
                 f"{n_devices}; running unsharded")
        return 1, 1
    return d, m


def make_serve_mesh(data: int, model: int,
                    devices: Optional[Sequence] = None) -> Mesh:
    """The serving engine's (data, model) mesh — a MeshConfig slice of
    the framework mesh (seq=pipe=1), so every PartitionSpec axis name
    used anywhere in the framework stays valid on it."""
    return make_mesh(MeshConfig(data=data, model=model), devices=devices)


def page_pool_pspec(cfg: ModelConfig, n_pages: int, data: int,
                    model: int) -> P:
    """The paged KV pool's PartitionSpec (table above), with
    non-divisible axes dropped to replication the same way `_leaf_spec`
    drops TP dims — a 7-page pool on data=2 replicates pages rather
    than pad-sharding them."""
    d_ax = "data" if data > 1 and n_pages % data == 0 else None
    if cfg.decode_cache_layout == "packed":
        axes = (None, d_ax, None,
                "model" if model > 1 and cfg.n_embd % model == 0 else None)
    else:
        axes = (None, d_ax,
                "model" if model > 1 and cfg.n_head % model == 0 else None,
                None, None)
    # trailing Nones trimmed: jit NORMALIZES output specs this way, and
    # the engine's jit caches key on input shardings — an untrimmed
    # spec here would make "cache fresh from device_put" and "cache
    # from the previous program's output" two different programs (a
    # recompile per step, caught by CompileGuard)
    while axes and axes[-1] is None:
        axes = axes[:-1]
    return P(*axes)


def page_scale_pspec(n_pages: int, data: int) -> P:
    """PartitionSpec of a quantized pool's ``ks``/``vs`` scale arrays
    ((L, n_pages, page[, H]) — quant/kv.py): the page axis shards over
    'data' EXACTLY like the pool itself (``page_pool_pspec``'s d_ax
    rule, divisibility drop included), so each chip stores the scale
    rows of precisely the pages it stores; the remaining axes
    replicate (scale metadata is ~1/C of the pool's bytes — sharding
    its model dim buys nothing). Trailing Nones trimmed for the same
    jit-cache-representation reason as the pool spec."""
    d_ax = "data" if data > 1 and n_pages % data == 0 else None
    axes = (None, d_ax)
    while axes and axes[-1] is None:
        axes = axes[:-1]
    return P(*axes)


@dataclass(frozen=True)
class ServeShardings:
    """The sharding bundle threaded through every device program the
    engine owns (a STATIC jit argument: hashable, one value per
    engine). ``cache`` pins the page pool's layout inside every traced
    program — donation aliases input to output only when their
    shardings match, so the pool spec must survive each scan body
    unchanged; ``rep`` pins the per-slot step state and the sampled
    token block to full replication (the host fetch stays local).

    ``rep2`` is the same full replication in the RANK-2 spec
    representation ``P(None, None)``: the jit cache key is
    representational (``P() != P(None, None)`` even though both mean
    replicated), a no-op with_sharding_constraint does not rewrite the
    propagated representation, and the window program's (B, 2) rng
    streams propagate out rank-matched — so the engine's bootstrap
    commit of the rng state must use this representation or the first
    steady-state dispatch after it compiles the same program twice
    (caught by CompileGuard, pinned in tests/test_serve_mesh.py)."""

    cache: NamedSharding
    rep: NamedSharding
    rep2: NamedSharding
    #: quantized-pool scale arrays (``ks``/``vs`` — page axis over
    #: 'data' via page_scale_pspec); present on every plan so the
    #: static bundle's hash does not depend on whether quantization is
    #: on (the pool dict's KEYS already key the programs)
    scale: NamedSharding = None


def serve_shardings(mesh: Mesh, cfg: ModelConfig, n_pages: int,
                    data: int, model: int) -> ServeShardings:
    return ServeShardings(
        cache=NamedSharding(mesh, page_pool_pspec(cfg, n_pages, data,
                                                  model)),
        rep=NamedSharding(mesh, P()),
        rep2=NamedSharding(mesh, P(None, None)),
        scale=NamedSharding(mesh, page_scale_pspec(n_pages, data)))


def serve_param_shardings(cfg: ModelConfig, mesh: Mesh, model: int,
                          params: Any = None) -> Any:
    """Decode-time parameter layout: Megatron TP over 'model',
    replicated over 'data' (the `shard_for_decode` rationale — no FSDP,
    no pipe at decode). ``params`` computes the specs from an ACTUAL
    tree instead of the init_params abstract structure — the
    weight-quantized tree (quant/weights.py) carries extra
    ``<name>_scale`` leaves (replicated: no TP name match) and int8
    kernels that keep their column/row TP dims by name."""
    if params is not None:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            state_pspecs(params, MeshConfig(model=model)))
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        param_pspecs(cfg, MeshConfig(model=model)))
