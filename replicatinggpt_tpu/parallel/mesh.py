"""Device mesh + partition rules: the framework's entire distributed layer.

The reference has no distributed machinery (SURVEY.md §2.1-§2.2). The
TPU-native replacement is declarative: build a ``jax.sharding.Mesh`` over
axes ``('data', 'seq', 'model')``, attach ``NamedSharding``s to the train
state and batches, and let XLA GSPMD insert the collectives (psum for DP
grad reduction, all-gather for FSDP parameter gathering, reduce-scatter /
all-reduce around the Megatron-style column/row-parallel matmuls) over
ICI/DCN. No hand-written transport code exists anywhere in the framework —
that is the point.

Partition rules (Megatron-style TP over 'model', SURVEY.md §2.1 table):

=====================  ==================  ==========================
param                  shape               spec (layer-stacked dim 0)
=====================  ==================  ==========================
wte                    (V, C)              ('model', None) — vocab-parallel
                                           embedding + tied head
lm_head (untied)       (C, V)              (None, 'model')
qkv_kernel             (L, C, 3C)          (None, None, 'model')  column
attn_out_kernel        (L, C, C)           (None, 'model', None)  row
mlp_up_kernel          (L, C, 4C)          (None, None, 'model')  column
mlp_down_kernel        (L, 4C, C)          (None, 'model', None)  row
biases of column ops   (L, K)              (None, 'model')
everything else        —                   replicated
=====================  ==================  ==========================

FSDP (``MeshConfig.fsdp``) additionally shards each param (and its Adam
moments, which inherit specs by tree-path) over 'data' on the largest
still-unsharded divisible dim — ZeRO-3 semantics for free under GSPMD.
Batches are (B, T) sharded ('data', 'seq').
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import MeshConfig, ModelConfig

# param-name → (tp_dim or None); dims are indices into the *unstacked* shape
# (block params carry a leading layer dim handled by offset)
_COLUMN_PARALLEL = {"qkv_kernel", "mlp_up_kernel"}
_COLUMN_BIAS = {"qkv_bias", "mlp_up_bias"}
_ROW_PARALLEL = {"attn_out_kernel", "mlp_down_kernel"}


def make_mesh(mesh_cfg: MeshConfig,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = mesh_cfg.n_devices
    assert len(devices) >= n, (
        f"mesh needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(
        mesh_cfg.data, mesh_cfg.seq, mesh_cfg.model, mesh_cfg.pipe)
    return Mesh(arr, mesh_cfg.axis_names)


def batch_pspec() -> P:
    return P("data", "seq")


def make_batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec())


def superbatch_pspec() -> P:
    """(K, B, T) stacked multi-step superbatch: the scan dim replicates,
    batch rows and sequence keep the (data, seq) layout of a single batch —
    so a K-step lax.scan dispatch sees each step's batch sharded exactly
    like the single-step path."""
    return P(None, "data", "seq")


def make_superbatch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, superbatch_pspec())


def _tp_spec(name: str, ndim: int) -> list:
    """Tensor-parallel placement for a leaf called ``name``."""
    spec = [None] * ndim
    if name == "wte":
        spec[0] = "model"
    elif name == "lm_head":
        spec[1] = "model"
    elif name in _COLUMN_PARALLEL:
        spec[ndim - 1] = "model"
    elif name in _COLUMN_BIAS:
        spec[ndim - 1] = "model"
    elif name in _ROW_PARALLEL:
        spec[ndim - 2] = "model"
    return spec


def _leaf_spec(path, shape: Tuple[int, ...], mesh_cfg: MeshConfig) -> P:
    """Spec for one leaf of the train state, identified by its tree path.

    Works uniformly for params and optimizer moments because optax's
    mu/nu subtrees mirror the params dict, so the param name appears as the
    final DictKey on the path either way.
    """
    name = None
    for k in reversed(path):
        if isinstance(k, jax.tree_util.DictKey):
            name = str(k.key)
            break
    in_blocks = any(isinstance(k, jax.tree_util.DictKey)
                    and str(k.key) == "blocks" for k in path)
    ndim = len(shape)
    spec = [None] * ndim
    if name is not None and ndim > 0:
        spec = _tp_spec(name, ndim)
        # drop TP sharding where the dim isn't divisible by the axis size
        for d, ax in enumerate(spec):
            if ax == "model" and shape[d] % mesh_cfg.model != 0:
                spec[d] = None
    # pipeline: each stage stores its slice of the layer-stacked (L, ...) dim
    if (mesh_cfg.pipe > 1 and in_blocks and ndim > 0
            and shape[0] % mesh_cfg.pipe == 0):
        spec[0] = "pipe"
    if mesh_cfg.fsdp and ndim > 0:
        # shard the largest unsharded divisible dim over 'data' (ZeRO-3)
        dims = sorted(range(ndim), key=lambda d: -shape[d])
        for d in dims:
            if spec[d] is None and shape[d] % mesh_cfg.data == 0 \
                    and shape[d] >= mesh_cfg.data:
                spec[d] = "data"
                break
    return P(*spec)


def state_pspecs(tree: Any, mesh_cfg: MeshConfig) -> Any:
    """PartitionSpec pytree for any state-shaped tree (TrainState, params,
    opt_state, ...). Scalars / unnamed leaves replicate."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _leaf_spec(path, tuple(leaf.shape), mesh_cfg),
        tree)


def state_shardings(tree: Any, mesh: Mesh, mesh_cfg: MeshConfig) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), state_pspecs(tree, mesh_cfg))


def param_pspecs(mcfg: ModelConfig, mesh_cfg: MeshConfig) -> Any:
    """Specs for just the model params (used by checkpoint restore and the
    HF importer)."""
    from ..models.gpt import init_params
    abstract = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), mcfg))
    return state_pspecs(abstract, mesh_cfg)


def shard_train_state(create_fn: Callable[[], Any], mesh: Mesh,
                      mesh_cfg: MeshConfig) -> Any:
    """Initialize train state directly in its sharded layout: jit the
    initializer with out_shardings so every device materializes only its own
    parameter/optimizer shards (no host-side full copy)."""
    abstract = jax.eval_shape(create_fn)
    shardings = state_shardings(abstract, mesh, mesh_cfg)
    with jax.set_mesh(mesh):
        return jax.jit(create_fn, out_shardings=shardings)()
