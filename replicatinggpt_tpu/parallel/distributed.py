"""Multi-host (pod-slice) runtime: the framework's DCN story.

The reference is strictly single-process (SURVEY.md §2.2 — no NCCL/MPI/
torch.distributed anywhere). The TPU-native equivalent is *not* a transport
backend: ``jax.distributed.initialize()`` joins the processes of a pod
slice, after which the same ``Mesh`` + ``NamedSharding`` annotations used
single-host make XLA route collectives over ICI within a slice and DCN
across slices. What this module adds on top is the host-side glue a
multi-process data-parallel run actually needs:

- :func:`initialize` — idempotent ``jax.distributed.initialize`` wrapper
  (auto-detects TPU pod environments when no coordinator is given; no-op
  for single-process runs).
- :func:`local_batch_slice` — which rows of the global batch this process
  must produce (each host feeds only its shard; per-host seeds derive from
  the global seed + process index).
- :func:`global_batch` — assemble a globally-sharded (B, T) array from
  this process's local rows via ``jax.make_array_from_process_local_data``
  (no host ever materializes the global batch).

Single-process runs pass through all of these unchanged, so the training
loop has exactly one code path.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> Tuple[int, int]:
    """Join the multi-process runtime. Returns (process_index, process_count).

    With no arguments on a TPU pod slice, jax auto-detects the topology
    from the TPU environment; on a single host this is a no-op. Safe to
    call more than once.
    """
    global _initialized
    if _initialized:
        return jax.process_index(), jax.process_count()
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        _initialized = True
    elif jax.process_count() > 1:
        _initialized = True  # runtime already multi-process (launcher did it)
    return jax.process_index(), jax.process_count()


def local_batch_slice(global_batch_size: int) -> slice:
    """Rows of the global (B, T) batch owned by this process.

    Processes split the batch dim evenly; B must divide by process_count
    (same contract the mesh 'data' axis imposes).
    """
    n, i = jax.process_count(), jax.process_index()
    assert global_batch_size % n == 0, (
        f"global batch {global_batch_size} not divisible by "
        f"{n} processes")
    per = global_batch_size // n
    return slice(i * per, (i + 1) * per)


def per_process_seed(seed: int) -> int:
    """Decorrelate host-side batch sampling across processes.

    Spaced 16 apart so callers can derive a few offset seeds (+1, +2 for
    eval batchers) without colliding with a neighbor process's streams.
    """
    return seed * 1000003 + 16 * jax.process_index()


def global_batch(local_rows: np.ndarray, sharding,
                 batch_axis: int = 0) -> jax.Array:
    """Assemble the global array from this process's local rows.

    ``local_rows``: NumPy array whose ``batch_axis`` dim holds this
    process's B/process_count rows — (B_local, T) for a single batch, or
    (K, B_local, T) with ``batch_axis=1`` for a stacked multi-step
    superbatch. ``sharding``: the NamedSharding of the global array
    (P('data','seq') / P(None,'data','seq')). Each process contributes only
    its rows — the global batch never exists on any one host.
    Single-process: equivalent to ``jax.device_put``.
    """
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    global_shape = list(local_rows.shape)
    global_shape[batch_axis] *= jax.process_count()
    return jax.make_array_from_process_local_data(
        sharding, local_rows, tuple(global_shape))


def is_coordinator() -> bool:
    """True on the process that should write checkpoints/logs (process 0)."""
    return jax.process_index() == 0
