"""Ulysses-style sequence parallelism: all-to-all head<->sequence resharding
around the attention core (the DeepSpeed-Ulysses recipe, re-expressed as XLA
``lax.all_to_all`` over the 'seq' mesh axis).

Alternative to ring attention (parallel/ring_attention.py) for the same
capability gap — the reference's hard single-device sequence cap
(GPT1.py:106, GPT-2.py:109). Where the ring keeps queries resident and
rotates KV chunks hop-by-hop, Ulysses does one all-to-all that trades the
sequence sharding for a head sharding: each device goes from holding
(B, H, T/n, D) — all heads, a sequence slice — to (B, H/n, T, D) — a head
slice, the full sequence — runs an ordinary *local* causal attention
(einsum or the Pallas flash kernel, since it now sees the whole sequence),
and a second all-to-all restores the sequence sharding. Two collectives per
attention call, both pure ICI all-to-alls, vs the ring's n ppermute hops;
requires local head count divisible by the seq axis size (the ring has no
such constraint).

Composable with tensor parallelism: heads arrive already sharded over
'model', and Ulysses further splits the *local* head dim over 'seq'.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .compat import axis_size, shard_map

from ..ops.attention import full_causal_attention


def _ulysses_local(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   key: Optional[jax.Array] = None, *,
                   axis_name: str, scale: Optional[float], impl: str,
                   dropout_rate: float = 0.0) -> jnp.ndarray:
    n = axis_size(axis_name)
    H = q.shape[1]
    assert H % n == 0, (
        f"Ulysses needs local head count {H} divisible by seq axis {n} "
        f"(use ring attention otherwise)")
    # seq-sharded (B, H, T/n, D) -> head-sharded (B, H/n, T, D)
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            split_axis=1, concat_axis=2, tiled=True)
    qh, kh, vh = a2a(q), a2a(k), a2a(v)
    if key is not None:
        # every device holds a distinct (batch, head-group) after the
        # all-to-all and emits only its own output shard, so masks
        # decorrelate over all three sharded axes
        shard = ((jax.lax.axis_index("data") * axis_size("model")
                  + jax.lax.axis_index("model")) * n
                 + jax.lax.axis_index(axis_name))
        key = jax.random.fold_in(key, shard)
    # full sequence locally -> plain causal mask is globally correct;
    # dropout runs in the local core (in-kernel on the flash path)
    out = full_causal_attention(qh, kh, vh, scale=scale, impl=impl,
                                dropout_rate=dropout_rate, rng=key,
                                train=key is not None)
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=2,
                              concat_axis=1, tiled=True)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                      mesh: Mesh, scale: Optional[float] = None,
                      seq_axis: str = "seq", impl: str = "einsum",
                      dropout_rate: float = 0.0,
                      rng: Optional[jax.Array] = None,
                      train: bool = False) -> jnp.ndarray:
    """Causal attention over a 'seq'-sharded sequence via head all-to-all.

    q, k, v: global (B, H, T, D), T sharded over ``seq_axis`` (B over
    'data', H over 'model'). Same contract as
    ``ring_attention.ring_attention``, including in-core attention-weight
    dropout when ``dropout_rate`` > 0 with ``rng`` while training.
    """
    spec = P("data", "model", seq_axis, None)
    local = functools.partial(_ulysses_local, axis_name=seq_axis,
                              scale=scale, impl=impl,
                              dropout_rate=dropout_rate)
    if not (train and dropout_rate > 0.0 and rng is not None):
        fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
        return fn(q, k, v)
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec, P()),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v, rng)


def make_ulysses_attention_fn(mesh: Mesh, scale: Optional[float] = None,
                              impl: str = "einsum",
                              dropout_rate: float = 0.0):
    """attention_fn for ``models.gpt.forward`` / ``train.steps``."""
    def attention_fn(q, k, v, rng=None, train=False):
        return ulysses_attention(q, k, v, mesh=mesh, scale=scale, impl=impl,
                                 dropout_rate=dropout_rate, rng=rng,
                                 train=train)
    return attention_fn
