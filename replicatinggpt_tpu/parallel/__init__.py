from .mesh import (make_mesh, make_batch_sharding, batch_pspec, state_pspecs,
                   param_pspecs, shard_train_state)
from .pipeline import make_pipeline_blocks_fn, pipeline_blocks
from .ring_attention import make_ring_attention_fn, ring_attention
from .ulysses import make_ulysses_attention_fn, ulysses_attention

__all__ = ["make_mesh", "make_batch_sharding", "batch_pspec", "state_pspecs",
           "param_pspecs", "shard_train_state", "ring_attention",
           "make_ring_attention_fn", "ulysses_attention",
           "make_ulysses_attention_fn", "select_attention_fn",
           "pipeline_blocks", "make_pipeline_blocks_fn", "select_blocks_fn"]


def select_attention_fn(mcfg, mesh_cfg, mesh):
    """Pick the sequence-parallel attention core for a (config, mesh) pair.

    Returns None — use the local einsum/flash core, GSPMD handles any
    sharding (including gathering a seq-sharded KV) — unless the mesh
    shards the sequence axis AND the configured impl opts into an explicit
    seq-parallel core. 'ulysses' / 'ring' select their path directly;
    'auto' is measurement-driven (benchmarks/seq_parallel_bench.py →
    benchmarks/SEQ_PARALLEL.md): Ulysses whenever the head count divides
    by the seq axis — 1.7-2.2x faster fwd+bwd on the 8-way virtual mesh at
    T∈{4k,8k}, ~n/2x less collective traffic analytically, and its local
    core sees the full sequence so the Pallas flash kernel applies — ring
    otherwise (no head-divisibility constraint). An explicit 'einsum' or
    'flash' is respected as-is.
    """
    if mesh is None or mesh_cfg.seq <= 1:
        return None
    impl = mcfg.attention_impl
    if impl == "auto":
        # Ulysses shards local heads over 'seq'; heads may already be
        # sharded over 'model' (TP), so the constraint is on local heads
        local_heads = mcfg.n_head // max(mesh_cfg.model, 1)
        impl = "ulysses" if local_heads % mesh_cfg.seq == 0 else "ring"
    if impl == "ulysses":
        # inside the Ulysses region each device sees the full sequence;
        # use the flash kernel there on TPU (einsum elsewhere — the pallas
        # interpreter is too slow to be a win off-TPU)
        import jax
        local = "flash" if jax.default_backend() == "tpu" else "einsum"
        return make_ulysses_attention_fn(mesh, impl=local,
                                         dropout_rate=mcfg.attn_dropout)
    if impl == "ring":
        return make_ring_attention_fn(mesh,
                                      dropout_rate=mcfg.attn_dropout)
    return None


def select_blocks_fn(mcfg, mesh_cfg, mesh):
    """Pipeline-parallel block stack when the mesh has a pipe axis > 1
    (supersedes attention_fn — the PP region runs its own in-scope ring
    attention core over 'seq')."""
    if mesh is None or mesh_cfg.pipe <= 1:
        return None
    return make_pipeline_blocks_fn(mesh, mesh_cfg)
