from .mesh import (make_mesh, make_batch_sharding, batch_pspec, state_pspecs,
                   param_pspecs, shard_train_state)

__all__ = ["make_mesh", "make_batch_sharding", "batch_pspec", "state_pspecs",
           "param_pspecs", "shard_train_state"]
