from .mesh import (make_mesh, make_batch_sharding, batch_pspec, state_pspecs,
                   param_pspecs, shard_train_state)
from .pipeline import make_pipeline_blocks_fn, pipeline_blocks
from .ring_attention import make_ring_attention_fn, ring_attention
from .ulysses import make_ulysses_attention_fn, ulysses_attention

__all__ = ["make_mesh", "make_batch_sharding", "batch_pspec", "state_pspecs",
           "param_pspecs", "shard_train_state", "ring_attention",
           "make_ring_attention_fn", "ulysses_attention",
           "make_ulysses_attention_fn", "select_attention_fn",
           "pipeline_blocks", "make_pipeline_blocks_fn", "select_blocks_fn"]


def select_attention_fn(mcfg, mesh_cfg, mesh):
    """Pick the sequence-parallel attention core for a (config, mesh) pair.

    Returns None — use the local einsum/flash core, GSPMD handles any
    sharding (including gathering a seq-sharded KV) — unless the mesh
    shards the sequence axis AND the configured impl opts into an explicit
    seq-parallel core: 'ulysses' selects the all-to-all path, 'ring'/'auto'
    the ppermute ring. An explicit 'einsum' or 'flash' is respected as-is
    (einsum is the only core with attention-weight dropout).
    """
    if mesh is None or mesh_cfg.seq <= 1:
        return None
    if mcfg.attention_impl == "ulysses":
        # inside the Ulysses region each device sees the full sequence;
        # use the flash kernel there on TPU (einsum elsewhere — the pallas
        # interpreter is too slow to be a win off-TPU)
        import jax
        local = "flash" if jax.default_backend() == "tpu" else "einsum"
        return make_ulysses_attention_fn(mesh, impl=local)
    if mcfg.attention_impl in ("auto", "ring"):
        return make_ring_attention_fn(mesh)
    return None


def select_blocks_fn(mcfg, mesh_cfg, mesh):
    """Pipeline-parallel block stack when the mesh has a pipe axis > 1
    (supersedes attention_fn — the PP region runs its own in-scope ring
    attention core over 'seq')."""
    if mesh is None or mesh_cfg.pipe <= 1:
        return None
    return make_pipeline_blocks_fn(mesh, mesh_cfg)
