from .mesh import (make_mesh, make_batch_sharding, batch_pspec, state_pspecs,
                   param_pspecs, shard_train_state)
from .pipeline import make_pipeline_blocks_fn, pipeline_blocks
from .ring_attention import make_ring_attention_fn, ring_attention
from .sharded_flash import (make_sharded_flash_attention_fn,
                            sharded_flash_attention)
from .ulysses import make_ulysses_attention_fn, ulysses_attention

__all__ = ["make_mesh", "make_batch_sharding", "batch_pspec", "state_pspecs",
           "param_pspecs", "shard_train_state", "ring_attention",
           "make_ring_attention_fn", "ulysses_attention",
           "make_ulysses_attention_fn", "sharded_flash_attention",
           "make_sharded_flash_attention_fn", "select_attention_fn",
           "pipeline_blocks", "make_pipeline_blocks_fn", "select_blocks_fn"]


def select_attention_fn(mcfg, mesh_cfg, mesh):
    """Pick the mesh-aware attention core for a (config, mesh) pair.

    Two regimes:

    - 'seq' axis > 1: an explicit sequence-parallel core. 'ulysses' /
      'ring' select their path directly; 'auto' is measurement-driven
      (benchmarks/seq_parallel_bench.py → benchmarks/SEQ_PARALLEL.md):
      Ulysses whenever the head count divides by the seq axis — 1.7-2.2x
      faster fwd+bwd on the 8-way virtual mesh at T∈{4k,8k}, ~n/2x less
      collective traffic analytically, and its local core sees the full
      sequence so the Pallas flash kernel applies — ring otherwise (no
      head-divisibility constraint).
    - no 'seq' axis (pure DP / FSDP / TP): the batch/head-parallel
      shard_map flash wrapper (parallel/sharded_flash.py) whenever the
      local policy would pick the Pallas kernel — TPU backend, T at or
      past the measured flash crossover, local heads divisible by the
      'model' axis. Without it, mesh runs would have to degrade to dense
      O(T²) einsum because pallas_call has no GSPMD partitioning rule.
      An explicit attention_impl='flash' forces the wrapper on any
      backend (the local core still falls back to SDPA/einsum off-TPU,
      so virtual-mesh dryruns exercise the same program structure).

    Returns None when plain GSPMD on the einsum core is the right
    answer: no mesh, explicit 'einsum', or sub-crossover sequence
    lengths off the Pallas envelope.
    """
    if mesh is None:
        return None
    if mesh_cfg.seq <= 1:
        import jax

        from ..ops.flash_attention import FLASH_MIN_T
        impl = mcfg.attention_impl
        if impl in ("auto", "ring", "ulysses"):
            # ring/ulysses need a seq axis; without one they mean 'auto'.
            # Conservative gates for 'auto': TP-indivisible heads would
            # make the wrapper gather heads per call, and off-TPU /
            # sub-crossover T the kernel wouldn't run anyway — plain
            # GSPMD einsum is the right core for all of those.
            on_tpu = jax.default_backend() == "tpu"
            if (not on_tpu or mcfg.block_size < FLASH_MIN_T
                    or (mesh_cfg.model > 1
                        and mcfg.n_head % mesh_cfg.model != 0)):
                return None
            impl = "flash"
        if impl == "flash":
            # Explicit 'flash' always wraps — the wrapper self-guards
            # against indivisible batch/head dims (dropping the axis from
            # its specs rather than degrading the whole run to dense
            # einsum). A resolved 'auto' keeps the per-T crossover policy
            # in the local core.
            local = ("flash" if mcfg.attention_impl == "flash" else "auto")
            fn = make_sharded_flash_attention_fn(
                mesh, impl=local, dropout_rate=mcfg.attn_dropout)
            fn.impl_name = "shard_map-flash"
            return fn
        return None  # explicit 'einsum'
    impl = mcfg.attention_impl
    if impl == "flash":
        # seq-sharded mesh: the memory-efficient request is honored by a
        # seq-parallel core whose local core is the flash kernel — a bare
        # pallas_call can't partition over 'seq', and degrading to dense
        # GSPMD einsum would materialize the O(T^2) weights the user
        # explicitly opted out of
        impl = "auto"
    if impl == "auto":
        # Ulysses shards local heads over 'seq'; heads may already be
        # sharded over 'model' (TP), so the constraint is on local heads
        local_heads = mcfg.n_head // max(mesh_cfg.model, 1)
        impl = "ulysses" if local_heads % mesh_cfg.seq == 0 else "ring"
    if impl == "ulysses":
        # inside the Ulysses region each device sees the full sequence;
        # use the flash kernel there on TPU (einsum elsewhere — the pallas
        # interpreter is too slow to be a win off-TPU)
        import jax
        local = "flash" if jax.default_backend() == "tpu" else "einsum"
        fn = make_ulysses_attention_fn(mesh, impl=local,
                                       dropout_rate=mcfg.attn_dropout)
        fn.impl_name = "ulysses"
        return fn
    if impl == "ring":
        fn = make_ring_attention_fn(mesh, dropout_rate=mcfg.attn_dropout)
        fn.impl_name = "ring"
        return fn
    return None


def select_blocks_fn(mcfg, mesh_cfg, mesh):
    """Pipeline-parallel block stack when the mesh has a pipe axis > 1
    (supersedes attention_fn — the PP region runs its own in-scope ring
    attention core over 'seq')."""
    if mesh is None or mesh_cfg.pipe <= 1:
        return None
    return make_pipeline_blocks_fn(mesh, mesh_cfg)
