"""jax version-compat shims for the parallel layer.

``shard_map`` graduated out of ``jax.experimental.shard_map`` into the
top-level ``jax`` namespace (and its ``check_rep`` keyword was renamed
``check_vma``) across jax releases. The call sites in this package are
written against the modern spelling; on an older jax this module falls
back to the experimental import and translates the keyword, so the
sequence/tensor-parallel suites run on either side of the rename
instead of dying with ``AttributeError: module 'jax' has no attribute
'shard_map'`` at collection.
"""

from __future__ import annotations

import functools

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    @functools.wraps(_exp_shard_map)
    def shard_map(f, *args, **kwargs):
        # modern keyword on the old API: check_vma -> check_rep
        if "check_vma" in kwargs and "check_rep" not in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _exp_shard_map(f, *args, **kwargs)


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        # pre-rename jax has no static accessor; psum of 1 over the
        # axis is the classic spelling and constant-folds at trace time
        return jax.lax.psum(1, axis_name)


if hasattr(jax, "set_mesh"):
    set_mesh = jax.set_mesh
elif hasattr(jax.sharding, "use_mesh"):
    set_mesh = jax.sharding.use_mesh
else:
    def set_mesh(mesh):
        # a Mesh is itself a context manager activating its axis names
        return mesh


__all__ = ["shard_map", "set_mesh", "axis_size"]
