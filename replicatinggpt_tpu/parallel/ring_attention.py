"""Ring attention: causal self-attention with the sequence axis sharded
across devices ('seq' mesh axis), KV blocks rotating around the ring via
``lax.ppermute`` over ICI.

The reference caps context at block_size because attention materializes the
full (T, T) weight matrix on one device (GPT1.py:106,114-116; the assert at
GPT-2.py:109). This module removes the single-device sequence cap: each of
the ``n`` devices on the 'seq' axis holds a (B, H, T/n, D) shard of q/k/v,
and at ring step ``s`` device ``i`` computes the attention block between its
local queries and the KV chunk originating on device ``(i - s) mod n``,
accumulated with the online-softmax recurrence (running max ``m``, running
normalizer ``l``, rescaled accumulator) so nothing bigger than a
(T/n, T/n) score tile ever exists. KV chunks move one hop per step
(device j -> j+1), so the collective is a neighbor ``ppermute`` that rides
ICI links, overlapping with the local block matmul.

Causality falls out of masking on *global* positions (chunk_index * T_local
+ local offset) — the diagonal block gets a triangular mask, blocks from
earlier chunks are unmasked, blocks from later chunks mask to -inf and
contribute nothing. The loop is a ``lax.scan`` with static trip count
``n``, so the whole ring is reverse-mode differentiable (the VJP of
``ppermute`` is the inverse rotation, and XLA overlaps those transfers the
same way).

Composition: ``make_ring_attention_fn(mesh)`` returns an ``attention_fn``
for ``models.gpt.forward`` — a ``jax.shard_map`` region over the mesh whose
'data' and 'model' axes are plain partitioning (batch, heads) and whose
'seq' axis carries the ring. It drops into the otherwise-GSPMD training
step; XLA stitches the sharding transitions.

Note: the ring core has no attention-weight dropout (GPT1.py:117); callers
training with ``attn_dropout > 0`` should disable it or accept the
deviation (recorded in PARITY.md). (The single-chip flash path lost this
limitation in round 2 — it applies dropout in-kernel, flash_pallas.py.)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import NEG_INF


def _ring_local(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                axis_name: str, scale: Optional[float]) -> jnp.ndarray:
    """Per-device ring attention body. q/k/v: local (B, H, T_local, D)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    if scale is None:
        scale = D ** -0.5

    qf = q.astype(jnp.float32) * scale
    qpos = idx * Tl + jax.lax.broadcasted_iota(jnp.int32, (Tl, Tl), 0)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def block_update(acc, m, l, k_cur, v_cur, src):
        """Online-softmax accumulation of one (Tl, Tl) score block against
        the KV chunk originating on device ``src``."""
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf,
                            k_cur.astype(jnp.float32),
                            preferred_element_type=jnp.float32)
        kpos = src * Tl + jax.lax.broadcasted_iota(jnp.int32, (Tl, Tl), 1)
        logits = jnp.where(kpos <= qpos, logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    # step 0 is the resident diagonal block — no rotation needed for it, and
    # peeling it keeps the scan at n-1 rotations (no dead final ppermute)
    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    m0 = jnp.full((B, H, Tl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    acc, m, l = block_update(acc0, m0, l0, k, v, idx)

    def step(carry, s):
        acc, m, l, k_cur, v_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (idx - s) % n  # chunk id the rotating KV now holds
        # chunks from the future (src > idx) are fully causal-masked —
        # their block_update is all wasted FLOPs. The predicate is
        # per-device (axis_index), which XLA:TPU lowers to a real
        # conditional, so each device does only its causal share and the
        # ring's total compute matches flash-style block skipping.
        acc, m, l = jax.lax.cond(
            src <= idx,
            lambda a, mm, ll: block_update(a, mm, ll, k_cur, v_cur, src),
            lambda a, mm, ll: (a, mm, ll),
            acc, m, l)
        return (acc, m, l, k_cur, v_cur), None

    if n > 1:
        # n == 1 (e.g. a degenerate seq axis inside the pipeline region)
        # must skip the rotation scan entirely: a zero-trip scan carries a
        # size-0 xs array whose cotangent trips XLA sharding-override
        # assertions under shard_map transpose — and it is dead code anyway
        (acc, _, l, _, _), _ = jax.lax.scan(
            step, (acc, m, l, k, v), jnp.arange(1, n))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   mesh: Mesh, scale: Optional[float] = None,
                   seq_axis: str = "seq") -> jnp.ndarray:
    """Causal ring attention over a sharded sequence.

    q, k, v: global (B, H, T, D) with T sharded over ``seq_axis`` (and
    optionally B over 'data', H over 'model'). Returns (B, H, T, D) with the
    same sharding. T must divide evenly by the seq axis size.
    """
    spec = P("data", "model", seq_axis, None)
    fn = jax.shard_map(
        functools.partial(_ring_local, axis_name=seq_axis, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def make_ring_attention_fn(mesh: Mesh, scale: Optional[float] = None):
    """attention_fn for ``models.gpt.forward`` / ``train.steps`` — plugs the
    sharded ring core into the per-block attention slot."""
    def attention_fn(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, scale=scale)
    return attention_fn
