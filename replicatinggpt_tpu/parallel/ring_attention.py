"""Ring attention: causal self-attention with the sequence axis sharded
across devices ('seq' mesh axis), KV blocks rotating around the ring via
``lax.ppermute`` over ICI.

The reference caps context at block_size because attention materializes the
full (T, T) weight matrix on one device (GPT1.py:106,114-116; the assert at
GPT-2.py:109). This module removes the single-device sequence cap: each of
the ``n`` devices on the 'seq' axis holds a (B, H, T/n, D) shard of q/k/v,
and at ring step ``s`` device ``i`` computes the attention block between its
local queries and the KV chunk originating on device ``(i - s) mod n``,
accumulated with the online-softmax recurrence (running max ``m``, running
normalizer ``l``, rescaled accumulator) so nothing bigger than a
(T/n, T/n) score tile ever exists. KV chunks move one hop per step
(device j -> j+1), so the collective is a neighbor ``ppermute`` that rides
ICI links, overlapping with the local block matmul.

Causality falls out of masking on *global* positions (chunk_index * T_local
+ local offset) — the diagonal block gets a triangular mask, blocks from
earlier chunks are unmasked, blocks from later chunks mask to -inf and
contribute nothing. The loop is a ``lax.scan`` with static trip count
``n``, so the whole ring is reverse-mode differentiable (the VJP of
``ppermute`` is the inverse rotation, and XLA overlaps those transfers the
same way).

Composition: ``make_ring_attention_fn(mesh)`` returns an ``attention_fn``
for ``models.gpt.forward`` — a ``jax.shard_map`` region over the mesh whose
'data' and 'model' axes are plain partitioning (batch, heads) and whose
'seq' axis carries the ring. It drops into the otherwise-GSPMD training
step; XLA stitches the sharding transitions.

Attention-weight dropout (GPT1.py:117) applies inside the ring with the
framework's shared uint8/1-in-256-quantized scheme: the mask multiplies
the unnormalized p *after* the running normalizer l accumulates it (the
same normalized-weights semantics as the dense path and the flash
kernel's in-kernel mask), keyed per (device, hop, q-chunk) so every
(q, k) block — computed on exactly one device — draws an independent
stream. Per-hop score memory is bounded by ``q_chunk``: queries process
in chunks of at most that many rows (a lax.map, sequential), so nothing
bigger than a (B, H, q_chunk, T_local) tile exists no matter how large
the per-device sequence shard is.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .compat import axis_size, shard_map

from ..ops.attention import NEG_INF, uint8_inverted_dropout

# per-hop q-chunk row bound: peak score-tile memory is
# B * H * Q_CHUNK * T_local * 4 bytes instead of B * H * T_local^2 * 4
Q_CHUNK = 2048


def _flash_hop_supported(q) -> bool:
    """Envelope for running ring hops through the Pallas chunk kernels:
    the shared kernel-eligibility check (ops.flash_attention.
    _pallas_supported — TPU backend, lane-aligned shapes). No residency
    bound anymore: past flash_pallas.STREAM_KV_BYTES the chunk op
    auto-routes to its streamed kernels (kv/q axis on the pallas grid,
    O(block^2) VMEM), so arbitrarily long per-device shards keep a
    Pallas kernel instead of falling back to the q-chunked einsum
    body — exactly the long-per-shard runs ring attention exists for
    (round-3 verdict item 4)."""
    from ..ops.flash_attention import _pallas_supported

    return _pallas_supported(q)


def _ring_local_flash(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                      axis_name: str, scale: Optional[float],
                      dropout_rate: float = 0.0,
                      rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Ring body with Pallas chunk-attention hops.

    Each hop is one fused (o, lse) kernel call
    (ops.flash_pallas.pallas_flash_chunk) with global-position causal
    masking and in-kernel dropout; hops merge by the logsumexp
    recurrence in plain JAX, so the whole ring is differentiable through
    the kernels' custom VJPs. Per-hop HBM is O(B*H*Tl*D) — no (Tl, Tl)
    score materialization at all (vs the einsum body's q-chunked tiles).
    Below STREAM_KV_BYTES the kernel holds one (batch, head)'s K/V chunk
    resident in VMEM; past it the chunk op auto-routes to its streamed
    kernels (kv/q grid axis + VMEM scratch state), so shard length is
    bounded by HBM only.

    Dropout: the kernel's counter-hash mask keys on absolute (seed,
    program bh, q position, k position); positions are global here and
    every (q, k) pair is computed on exactly one device/hop, while
    ``rng`` arrives pre-folded per (data, model) shard, so streams never
    collide.
    """
    from ..ops.flash_pallas import pallas_flash_chunk

    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    q_off = idx * Tl

    def hop_attn(k_cur, v_cur, src):
        return pallas_flash_chunk(q, k_cur, v_cur, scale=scale, causal=True,
                                  q_offset=q_off, k_offset=src * Tl,
                                  dropout_rate=dropout_rate,
                                  dropout_rng=rng)

    def merge(o_acc, lse_acc, o_s, lse_s):
        # both lse's are finite on every executed hop: the diagonal hop's
        # rows attend at least themselves, earlier-chunk hops are fully
        # unmasked, and future chunks never execute (cond below)
        m = jnp.maximum(lse_acc, lse_s)
        w1 = jnp.exp(lse_acc - m)
        w2 = jnp.exp(lse_s - m)
        denom = w1 + w2
        o = (o_acc * w1[..., None] + o_s.astype(jnp.float32) * w2[..., None]
             ) / denom[..., None]
        return o, m + jnp.log(denom)

    o_acc, lse_acc = hop_attn(k, v, idx)  # resident diagonal block
    o_acc = o_acc.astype(jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, s):
        o_acc, lse_acc, k_cur, v_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (idx - s) % n

        def do_hop(o_a, lse_a):
            o_s, lse_s = hop_attn(k_cur, v_cur, src)
            return merge(o_a, lse_a, o_s, lse_s)

        o_acc, lse_acc = jax.lax.cond(src <= idx, do_hop,
                                      lambda a, b: (a, b), o_acc, lse_acc)
        return (o_acc, lse_acc, k_cur, v_cur), None

    if n > 1:
        (o_acc, _, _, _), _ = jax.lax.scan(
            step, (o_acc, lse_acc, k, v), jnp.arange(1, n))
    return o_acc.astype(q.dtype)


def _ring_local(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                axis_name: str, scale: Optional[float],
                dropout_rate: float = 0.0,
                rng: Optional[jax.Array] = None, train: bool = False,
                q_chunk: int = Q_CHUNK,
                hop_impl: str = "auto") -> jnp.ndarray:
    """Per-device ring attention body. q/k/v: local (B, H, T_local, D).

    ``rng`` must already be decorrelated across every sharded axis except
    ``axis_name`` (the ring folds in its own seq-axis index, hop and
    q-chunk); callers whose batch/heads are sharded fold those axis
    indices in first (ring_attention does this for the GSPMD wrapper).

    ``hop_impl``: 'einsum' (q-chunked XLA tiles, runs everywhere),
    'flash' (Pallas chunk kernel per hop — _ring_local_flash), or 'auto'
    (flash on TPU when the shape fits the kernel envelope).
    """
    if hop_impl not in ("auto", "flash", "einsum"):
        raise ValueError(f"hop_impl must be 'auto', 'flash' or 'einsum', "
                         f"got {hop_impl!r}")
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    if scale is None:
        scale = D ** -0.5
    dropping = train and dropout_rate > 0.0 and rng is not None
    if hop_impl == "flash" or (
            hop_impl == "auto" and _flash_hop_supported(q)):
        return _ring_local_flash(q, k, v, axis_name=axis_name, scale=scale,
                                 dropout_rate=dropout_rate if dropping
                                 else 0.0,
                                 rng=rng if dropping else None)
    key = (jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
           if dropping else None)
    # largest divisor of Tl that fits the chunk bound, so the per-hop
    # score-tile guarantee holds for every shard size (not only exact
    # multiples); trace-time loop, worst case q_chunk iterations
    qc = next(d for d in range(min(q_chunk, Tl), 0, -1) if Tl % d == 0)
    nc = Tl // qc

    qf = q.astype(jnp.float32) * scale

    def chunk_update(q_c, acc, m, l, k_cur, v_cur, src, c_idx, hop_key):
        """Online-softmax update of one (qc, Tl) score tile: this
        device's q rows [c_idx*qc, ...) against the KV chunk originating
        on device ``src``."""
        logits = jnp.einsum("bhqd,bhkd->bhqk", q_c,
                            k_cur.astype(jnp.float32),
                            preferred_element_type=jnp.float32)
        qpos = (idx * Tl + c_idx * qc
                + jax.lax.broadcasted_iota(jnp.int32, (qc, Tl), 0))
        kpos = src * Tl + jax.lax.broadcasted_iota(jnp.int32, (qc, Tl), 1)
        logits = jnp.where(kpos <= qpos, logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m - m_new)
        # l is dropout-free (dropout applies to the normalized weights);
        # only the V accumulation sees the inverted-dropout multiplier —
        # flash-kernel semantics (flash_pallas._fwd_tile)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if hop_key is not None:
            p = uint8_inverted_dropout(
                p, dropout_rate, jax.random.fold_in(hop_key, c_idx))
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    # remat the per-chunk update: its backward recomputes the (qc, Tl)
    # score/probability tiles from q/k/v instead of storing them as scan
    # residuals — without this the einsum ring saves O(T^2/n) f32 tiles
    # per hop (measured 76.8 GB/device at Tl=32k in the longctx
    # rehearsal; 0.82 GB with remat), which is the flash hops' recompute
    # semantics anyway (their custom VJP re-derives tiles from lse)
    chunk_update_r = jax.checkpoint(chunk_update)

    def block_update(acc, m, l, k_cur, v_cur, src, hop):
        hop_key = jax.random.fold_in(key, hop) if dropping else None
        if nc == 1:
            return chunk_update_r(qf, acc, m, l, k_cur, v_cur, src,
                                  jnp.int32(0), hop_key)

        def per_chunk(xs):
            q_c, acc_c, m_c, l_c, c_idx = xs
            return chunk_update_r(q_c, acc_c, m_c, l_c, k_cur, v_cur, src,
                                  c_idx, hop_key)

        def split(t):  # (B, H, Tl, X) -> (nc, B, H, qc, X)
            return jnp.moveaxis(
                t.reshape(B, H, nc, qc, t.shape[-1]), 2, 0)

        def join(t):
            return jnp.moveaxis(t, 0, 2).reshape(B, H, Tl, t.shape[-1])

        acc_n, m_n, l_n = jax.lax.map(
            per_chunk, (split(qf), split(acc), split(m), split(l),
                        jnp.arange(nc)))
        return join(acc_n), join(m_n), join(l_n)

    # step 0 is the resident diagonal block — no rotation needed for it, and
    # peeling it keeps the scan at n-1 rotations (no dead final ppermute)
    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    m0 = jnp.full((B, H, Tl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    acc, m, l = block_update(acc0, m0, l0, k, v, idx, jnp.int32(0))

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, s):
        acc, m, l, k_cur, v_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (idx - s) % n  # chunk id the rotating KV now holds
        # chunks from the future (src > idx) are fully causal-masked —
        # their block_update is all wasted FLOPs. The predicate is
        # per-device (axis_index), which XLA:TPU lowers to a real
        # conditional, so each device does only its causal share and the
        # ring's total compute matches flash-style block skipping.
        acc, m, l = jax.lax.cond(
            src <= idx,
            lambda a, mm, ll: block_update(a, mm, ll, k_cur, v_cur, src, s),
            lambda a, mm, ll: (a, mm, ll),
            acc, m, l)
        return (acc, m, l, k_cur, v_cur), None

    if n > 1:
        # n == 1 (e.g. a degenerate seq axis inside the pipeline region)
        # must skip the rotation scan entirely: a zero-trip scan carries a
        # size-0 xs array whose cotangent trips XLA sharding-override
        # assertions under shard_map transpose — and it is dead code anyway
        (acc, _, l, _, _), _ = jax.lax.scan(
            step, (acc, m, l, k, v), jnp.arange(1, n))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   mesh: Mesh, scale: Optional[float] = None,
                   seq_axis: str = "seq", dropout_rate: float = 0.0,
                   rng: Optional[jax.Array] = None,
                   train: bool = False,
                   hop_impl: str = "auto") -> jnp.ndarray:
    """Causal ring attention over a sharded sequence.

    q, k, v: global (B, H, T, D) with T sharded over ``seq_axis`` (and
    optionally B over 'data', H over 'model'). Returns (B, H, T, D) with the
    same sharding. T must divide evenly by the seq axis size.

    With ``dropout_rate`` > 0 (and ``rng``, while ``train``), inverted
    attention-weight dropout applies inside the ring. The replicated key
    is decorrelated per (data, model) shard here — batch elements and
    heads live on different devices and must not share mask streams —
    and per (seq device, hop, q-chunk) inside ``_ring_local``.
    """
    spec = P("data", "model", seq_axis, None)
    if not (train and dropout_rate > 0.0 and rng is not None):
        fn = shard_map(
            functools.partial(_ring_local, axis_name=seq_axis, scale=scale,
                              hop_impl=hop_impl),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return fn(q, k, v)

    def body(q, k, v, key):
        shard = (jax.lax.axis_index("data") * axis_size("model")
                 + jax.lax.axis_index("model"))
        return _ring_local(q, k, v, axis_name=seq_axis, scale=scale,
                           dropout_rate=dropout_rate,
                           rng=jax.random.fold_in(key, shard), train=True,
                           hop_impl=hop_impl)

    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec, P()),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v, rng)


def make_ring_attention_fn(mesh: Mesh, scale: Optional[float] = None,
                           dropout_rate: float = 0.0,
                           hop_impl: str = "auto"):
    """attention_fn for ``models.gpt.forward`` / ``train.steps`` — plugs the
    sharded ring core into the per-block attention slot. ``hop_impl``
    pins the per-hop body ('einsum' | 'flash' | 'auto')."""
    def attention_fn(q, k, v, rng=None, train=False):
        return ring_attention(q, k, v, mesh=mesh, scale=scale,
                              dropout_rate=dropout_rate, rng=rng,
                              train=train, hop_impl=hop_impl)
    return attention_fn
