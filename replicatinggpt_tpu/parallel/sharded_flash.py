"""Batch/head-parallel flash attention for meshes without a 'seq' axis.

The Pallas flash kernel (ops/flash_pallas.py) has no GSPMD partitioning
rule, so a jit-sharded program cannot call ``pallas_call`` directly — the
compiler would have to either replicate the kernel (wrong numbers) or fail
to lower. Sequence-parallel runs already solve this with explicit shard_map
regions (parallel/ring_attention.py, parallel/ulysses.py); this module is
the same move for the remaining — and most common — mesh shapes: pure DP,
FSDP, and TP, where attention is embarrassingly parallel per device
(batch sharded over 'data', heads over 'model', full sequence local).

The body runs the ordinary local attention core: the Pallas flash kernel
on TPU (the whole point — BASELINE configs 3/4 train at T=1024 where flash
is worth tens of percent, benchmarks/RESULTS.md), XLA SDPA / einsum
elsewhere. Attention-weight dropout decorrelates per (data, model) shard
by folding the device indices into the rng, mirroring the Ulysses wrapper.

The reference never loses its flash path on its device
(/root/reference/GPT-2.py:46); with this wrapper, neither do mesh runs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from .compat import axis_size, shard_map

from ..ops.attention import full_causal_attention
from ..ops.flash_attention import FLASH_MIN_T


def _local_attention(q, k, v, key=None, *, scale: Optional[float],
                     dropout_rate: float, impl: str, batch_axis, head_axis):
    """Per-device body: plain causal attention over the local
    (B/data, H/model, T, D) shard — no collectives; causality is exact
    because the full sequence is local. The rng folds in only the mesh
    axes that actually partition the block (devices along an unused axis
    compute identical replicated outputs and must stay bit-identical)."""
    if impl == "auto":
        impl = "flash" if q.shape[2] >= FLASH_MIN_T else "einsum"
    if key is not None:
        shard = jax.lax.axis_index(batch_axis) if batch_axis else 0
        if head_axis:
            shard = (shard * axis_size(head_axis)
                     + jax.lax.axis_index(head_axis))
        key = jax.random.fold_in(key, shard)
    return full_causal_attention(q, k, v, scale=scale, impl=impl,
                                 dropout_rate=dropout_rate, rng=key,
                                 train=key is not None)


def sharded_flash_attention(q, k, v, *, mesh: Mesh,
                            scale: Optional[float] = None,
                            impl: str = "auto",
                            dropout_rate: float = 0.0,
                            rng: Optional[jax.Array] = None,
                            train: bool = False):
    """Causal attention on a mesh whose 'seq' axis is 1.

    q, k, v: global (B, H, T, D) with B sharded over 'data' and H over
    'model' (the layout GSPMD produces from the batch sharding and the
    Megatron column-parallel qkv projection, parallel/mesh.py). Same
    attention_fn contract as the ring/Ulysses wrappers, including
    in-core attention-weight dropout.

    Self-guarding on shard_map's even-division requirement: an axis whose
    size does not divide the corresponding dim drops out of the specs
    (the body then sees that dim whole, at the cost of a gather), and if
    neither axis divides, the call falls back to the plain GSPMD einsum
    core — the envelope the wrapper replaced.
    """
    data_n = mesh.shape.get("data", 1)
    model_n = mesh.shape.get("model", 1)
    batch_axis = "data" if (data_n > 1 and q.shape[0] % data_n == 0) else None
    head_axis = "model" if (model_n > 1 and q.shape[1] % model_n == 0) \
        else None
    dropped = ((data_n > 1 and batch_axis is None)
               or (model_n > 1 and head_axis is None))
    if dropped and impl != "flash":
        # 'auto' must not degrade to replicated compute: dropping an
        # indivisible axis from the specs makes every device along it
        # gather and redundantly compute that whole dimension's
        # attention — strictly worse than the GSPMD einsum this wrapper
        # replaced. Only an explicit 'flash' (the user opting into the
        # memory-efficient kernel at any cost) pays the gather below.
        return full_causal_attention(q, k, v, scale=scale, impl="einsum",
                                     dropout_rate=dropout_rate, rng=rng,
                                     train=train)
    # Reaching here with both axes dropped means explicit 'flash' on a
    # mesh where nothing divides: the specs below are fully replicated,
    # every device computes the whole batch's attention redundantly —
    # wasteful, but memory-efficient and what the user asked for (dense
    # einsum at the long T that motivates 'flash' would materialize the
    # O(T^2) weights instead). Runtime-signal the N-fold redundancy once.
    if dropped:
        import warnings
        parts = []
        if data_n > 1 and batch_axis is None:
            parts.append(f"batch (B={q.shape[0]} vs data={data_n})")
        if model_n > 1 and head_axis is None:
            parts.append(f"heads (H={q.shape[1]} vs model={model_n})")
        warnings.warn(
            f"sharded flash attention: {' and '.join(parts)} do(es) not "
            "divide the mesh axis, so that dimension is replicated — "
            "every device along the dropped axis redundantly computes it "
            "(explicit impl='flash' opts into this for the "
            "memory-efficient kernel). Pad the dimension to a multiple "
            "of the mesh axis to shard the compute.",
            stacklevel=2)
    spec = P(batch_axis, head_axis, None, None)
    local = functools.partial(_local_attention, scale=scale,
                              dropout_rate=dropout_rate, impl=impl,
                              batch_axis=batch_axis, head_axis=head_axis)
    if not (train and dropout_rate > 0.0 and rng is not None):
        fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
        return fn(q, k, v)
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec, P()),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v, rng)


def _local_packed(qkv, key=None, *, n_head, scale: Optional[float],
                  dropout_rate: float):
    """Per-device body of the packed-qkv fast path: the packed-heads
    kernel on this device's batch shard, dropout stream folded per
    'data' shard (the in-kernel counter already decorrelates heads).
    Routes through ops.flash_attention.packed_qkv_attention — the one
    envelope-gating site — which cannot return None here because the
    hook prechecked the identical envelope before opening shard_map."""
    from ..ops.flash_attention import packed_qkv_attention
    if key is not None:
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
    out = packed_qkv_attention(qkv, n_head, scale=scale,
                               dropout_rate=dropout_rate, rng=key,
                               train=key is not None)
    assert out is not None, "packed envelope changed between gate and body"
    return out


def make_sharded_flash_attention_fn(mesh: Mesh,
                                    scale: Optional[float] = None,
                                    impl: str = "auto",
                                    dropout_rate: float = 0.0):
    """attention_fn for ``models.gpt.forward`` / ``train.steps``.

    On meshes that shard neither heads nor sequence (pure DP / FSDP),
    the returned fn also carries a ``packed_qkv`` hook: models.gpt._block
    offers it the fused (B, T, 3C) projection output so the packed-heads
    kernel family — the round-3 +45-50% char-GPT win — engages per
    device instead of paying the split/transpose round trip the
    (B, H, T, D) contract implies. The hook returns None off the packed
    envelope (non-TPU, indivisible batch, VMEM bound); _block then takes
    the ordinary split-heads path through this same wrapper.
    """
    def attention_fn(q, k, v, rng=None, train=False):
        return sharded_flash_attention(q, k, v, mesh=mesh, scale=scale,
                                       impl=impl, dropout_rate=dropout_rate,
                                       rng=rng, train=train)

    model_n = mesh.shape.get("model", 1)
    seq_n = mesh.shape.get("seq", 1)
    if model_n == 1 and seq_n == 1:
        def packed_qkv(qkv, n_head, rng=None, train=False):
            from ..ops.flash_attention import (FLASH_MIN_T,
                                               packed_envelope_ok)
            B, T, _ = qkv.shape
            data_n = mesh.shape.get("data", 1)
            if B % data_n != 0:
                return None
            if impl != "flash" and T < FLASH_MIN_T:
                return None  # 'auto' keeps the measured crossover
            if not packed_envelope_ok(qkv, n_head):
                return None
            spec = P("data", None, None)
            local = functools.partial(_local_packed, n_head=n_head,
                                      scale=scale,
                                      dropout_rate=dropout_rate)
            if not (train and dropout_rate > 0.0 and rng is not None):
                fn = shard_map(local, mesh=mesh, in_specs=(spec,),
                                   out_specs=spec, check_vma=False)
                return fn(qkv)
            fn = shard_map(local, mesh=mesh, in_specs=(spec, P()),
                               out_specs=spec, check_vma=False)
            return fn(qkv, rng)

        attention_fn.packed_qkv = packed_qkv
    return attention_fn
