"""Batch/head-parallel flash attention for meshes without a 'seq' axis.

The Pallas flash kernel (ops/flash_pallas.py) has no GSPMD partitioning
rule, so a jit-sharded program cannot call ``pallas_call`` directly — the
compiler would have to either replicate the kernel (wrong numbers) or fail
to lower. Sequence-parallel runs already solve this with explicit shard_map
regions (parallel/ring_attention.py, parallel/ulysses.py); this module is
the same move for the remaining — and most common — mesh shapes: pure DP,
FSDP, and TP, where attention is embarrassingly parallel per device
(batch sharded over 'data', heads over 'model', full sequence local).

The body runs the ordinary local attention core: the Pallas flash kernel
on TPU (the whole point — BASELINE configs 3/4 train at T=1024 where flash
is worth tens of percent, benchmarks/RESULTS.md), XLA SDPA / einsum
elsewhere. Attention-weight dropout decorrelates per (data, model) shard
by folding the device indices into the rng, mirroring the Ulysses wrapper.

The reference never loses its flash path on its device
(/root/reference/GPT-2.py:46); with this wrapper, neither do mesh runs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import full_causal_attention
from ..ops.flash_attention import FLASH_MIN_T


def _local_attention(q, k, v, key=None, *, scale: Optional[float],
                     dropout_rate: float, impl: str, batch_axis, head_axis):
    """Per-device body: plain causal attention over the local
    (B/data, H/model, T, D) shard — no collectives; causality is exact
    because the full sequence is local. The rng folds in only the mesh
    axes that actually partition the block (devices along an unused axis
    compute identical replicated outputs and must stay bit-identical)."""
    if impl == "auto":
        impl = "flash" if q.shape[2] >= FLASH_MIN_T else "einsum"
    if key is not None:
        shard = jax.lax.axis_index(batch_axis) if batch_axis else 0
        if head_axis:
            shard = (shard * jax.lax.axis_size(head_axis)
                     + jax.lax.axis_index(head_axis))
        key = jax.random.fold_in(key, shard)
    return full_causal_attention(q, k, v, scale=scale, impl=impl,
                                 dropout_rate=dropout_rate, rng=key,
                                 train=key is not None)


def sharded_flash_attention(q, k, v, *, mesh: Mesh,
                            scale: Optional[float] = None,
                            impl: str = "auto",
                            dropout_rate: float = 0.0,
                            rng: Optional[jax.Array] = None,
                            train: bool = False):
    """Causal attention on a mesh whose 'seq' axis is 1.

    q, k, v: global (B, H, T, D) with B sharded over 'data' and H over
    'model' (the layout GSPMD produces from the batch sharding and the
    Megatron column-parallel qkv projection, parallel/mesh.py). Same
    attention_fn contract as the ring/Ulysses wrappers, including
    in-core attention-weight dropout.

    Self-guarding on shard_map's even-division requirement: an axis whose
    size does not divide the corresponding dim drops out of the specs
    (the body then sees that dim whole, at the cost of a gather), and if
    neither axis divides, the call falls back to the plain GSPMD einsum
    core — the envelope the wrapper replaced.
    """
    data_n = mesh.shape.get("data", 1)
    model_n = mesh.shape.get("model", 1)
    batch_axis = "data" if (data_n > 1 and q.shape[0] % data_n == 0) else None
    head_axis = "model" if (model_n > 1 and q.shape[1] % model_n == 0) \
        else None
    if batch_axis is None and head_axis is None and (data_n > 1
                                                    or model_n > 1):
        # nothing shard_map-able: preserve the pre-wrapper behavior
        # (GSPMD einsum tolerates uneven sharding via padding)
        return full_causal_attention(q, k, v, scale=scale, impl="einsum",
                                     dropout_rate=dropout_rate, rng=rng,
                                     train=train)
    spec = P(batch_axis, head_axis, None, None)
    local = functools.partial(_local_attention, scale=scale,
                              dropout_rate=dropout_rate, impl=impl,
                              batch_axis=batch_axis, head_axis=head_axis)
    if not (train and dropout_rate > 0.0 and rng is not None):
        fn = jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
        return fn(q, k, v)
    fn = jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec, P()),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v, rng)


def make_sharded_flash_attention_fn(mesh: Mesh,
                                    scale: Optional[float] = None,
                                    impl: str = "auto",
                                    dropout_rate: float = 0.0):
    """attention_fn for ``models.gpt.forward`` / ``train.steps``."""
    def attention_fn(q, k, v, rng=None, train=False):
        return sharded_flash_attention(q, k, v, mesh=mesh, scale=scale,
                                       impl=impl, dropout_rate=dropout_rate,
                                       rng=rng, train=train)
    return attention_fn
