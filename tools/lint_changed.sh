#!/usr/bin/env bash
# Diff-aware graftlint: lint only the files that changed vs a ref
# (default origin/main, falling back to main, then HEAD), with the
# whole project still indexed so cross-file dataflow stays sound.
# Renamed/copied files count as changed under their NEW path.
# Intended as a pre-push hook:
#   ln -s ../../tools/lint_changed.sh .git/hooks/pre-push
#
# Exit codes (the linter's, passed through by exec):
#   0  no new error-tier findings in the changed files
#   1  at least one NEW finding (not baselined, not a tests/ warning)
#   2  usage error — unknown rule id, bad --severity spec, or (from
#      this wrapper) an argument that does not resolve to a commit
set -euo pipefail
# resolve symlinks first: installed as .git/hooks/pre-push, $0's dirname
# would otherwise land us in .git/
cd "$(dirname "$(readlink -f "$0")")/.."

# As a pre-push hook git invokes us as `pre-push <remote-name> <url>` —
# those are not refs; only honor $1 when invoked manually with a single
# argument. A single argument that does NOT resolve to a commit is a
# typo: fail loudly rather than silently linting against the default.
ref=""
if [ "$#" -eq 1 ]; then
    if ! git rev-parse --verify --quiet "$1^{commit}" >/dev/null; then
        echo "lint_changed.sh: '$1' does not resolve to a commit" >&2
        exit 2
    fi
    ref="$1"
fi
if [ -z "$ref" ]; then
    for cand in origin/main main HEAD; do
        if git rev-parse --verify --quiet "$cand^{commit}" >/dev/null; then
            ref="$cand"
            break
        fi
    done
fi

# tests/ stays warning-tier even here: a hook must apply the same
# gate the tier-1 run applies, or pushes fail on findings CI ignores
exec python -m replicatinggpt_tpu lint --baseline --changed "$ref" \
    --severity 'tests/=warning'
