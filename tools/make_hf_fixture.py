#!/usr/bin/env python
"""Create the golden GPT-2 124M import fixture (VERDICT r2 item 7).

Run once in an environment where the real HF ``gpt2`` weights are
available (downloaded or cached — this dev image has zero egress and no
cache, so the fixture ships empty until a networked run executes this):

    python tools/make_hf_fixture.py [--model gpt2] \
        [--out tests/fixtures/hf_gpt2_golden.npz]

It imports the real weights through ``interop.hf.from_pretrained``,
runs the framework forward on a fixed token sequence, and records
(input ids, a logits slice, loss) so ``tests/test_hf_import.py``'s
fixture test can re-verify the import mapping offline forever after —
independent of ``transformers``' model code or randomness.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2")
    p.add_argument("--out", default="tests/fixtures/hf_gpt2_golden.npz")
    args = p.parse_args()

    import jax
    import numpy as np

    from replicatinggpt_tpu.interop.hf import from_pretrained
    from replicatinggpt_tpu.models.gpt import forward

    params, mcfg = from_pretrained(args.model)
    # fixed, tokenizer-independent input: deterministic ids < 50257
    rng = np.random.default_rng(1337)
    ids = rng.integers(0, 50257, (2, 64), dtype=np.int32)
    logits, loss = forward(params, ids, mcfg, targets=ids)
    logits = np.asarray(jax.device_get(logits), np.float32)
    np.savez_compressed(
        args.out,
        model=args.model,
        input_ids=ids,
        # full logits for 2x64x50257 is ~25 MB; keep a dense slice plus
        # global moments — plenty to pin the mapping
        logits_slice=logits[:, :8, :256],
        logits_mean=np.float32(logits.mean()),
        logits_std=np.float32(logits.std()),
        loss=np.float32(jax.device_get(loss)),
    )
    print(f"wrote {args.out}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
