#!/usr/bin/env python
"""Create the golden GPT-2 124M import fixture (VERDICT r2 item 7).

Run once in an environment where the real HF ``gpt2`` weights are
available (downloaded or cached — this dev image has zero egress and no
cache, so the fixture ships empty until a networked run executes this):

    python tools/make_hf_fixture.py [--model gpt2] \
        [--out tests/fixtures/hf_gpt2_golden.npz]

It imports the real weights through ``interop.hf.from_pretrained``,
runs the framework forward on a fixed token sequence, and records
(input ids, a logits slice, loss) so ``tests/test_hf_import.py``'s
fixture test can re-verify the import mapping offline forever after —
independent of ``transformers``' model code or randomness.

With ``--synthetic`` it instead writes the network-free hermetic fixture
(synthetic deterministic weights + transformers-computed logits) that
``test_synthetic_golden_fixture_hermetic`` consumes with no torch at all.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_synthetic(out: str) -> None:
    """Create the SYNTHETIC hermetic fixture (no network): a small
    GPT2LMHeadModel with deterministic numpy-RNG weights, its HF-format
    state_dict, input ids, and the logits transformers computes — all
    recorded into one npz. ``tests/test_hf_import.py``'s hermetic test
    then re-runs ``import_hf_state_dict`` + our forward against the
    recorded logits with no torch/transformers dependency at test time,
    pinning the Conv1D-layout mapping numerics forever. (The REAL-gpt2
    fixture below still needs one networked run — this image has zero
    egress — but the mapping itself is the same code path.)"""
    import numpy as np
    import torch
    import transformers

    cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=48, n_embd=64, n_layer=3, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    model = transformers.GPT2LMHeadModel(cfg)
    model.eval()
    rng = np.random.default_rng(20260731)
    with torch.no_grad():
        # named_parameters deduplicates the tied lm_head/wte pair, so
        # each underlying tensor is assigned exactly once
        for _, p in model.named_parameters():
            p.copy_(torch.from_numpy(
                (rng.standard_normal(tuple(p.shape)) * 0.05)
                .astype(np.float32)))
    sd = {k: v.detach().cpu().numpy()
          for k, v in model.state_dict().items()}
    ids = rng.integers(0, 97, (2, 32), dtype=np.int32)
    with torch.no_grad():
        want = model(torch.from_numpy(ids).long()).logits.numpy()
    np.savez_compressed(
        out, input_ids=ids, logits=np.asarray(want, np.float32),
        **{f"sd__{k}": v for k, v in sd.items()})
    print(f"wrote {out}: {len(sd)} state_dict tensors, "
          f"logits {want.shape}")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2")
    p.add_argument("--out", default="tests/fixtures/hf_gpt2_golden.npz")
    p.add_argument("--synthetic", action="store_true",
                   help="write the network-free synthetic fixture to "
                        "tests/fixtures/hf_synthetic_golden.npz instead")
    args = p.parse_args()

    if args.synthetic:
        out = args.out
        if out == "tests/fixtures/hf_gpt2_golden.npz":
            out = "tests/fixtures/hf_synthetic_golden.npz"
        make_synthetic(out)
        return

    import jax
    import numpy as np

    from replicatinggpt_tpu.interop.hf import from_pretrained
    from replicatinggpt_tpu.models.gpt import forward

    params, mcfg = from_pretrained(args.model)
    # fixed, tokenizer-independent input: deterministic ids < 50257
    rng = np.random.default_rng(1337)
    ids = rng.integers(0, 50257, (2, 64), dtype=np.int32)
    logits, loss = forward(params, ids, mcfg, targets=ids)
    logits = np.asarray(jax.device_get(logits), np.float32)
    np.savez_compressed(
        args.out,
        model=args.model,
        input_ids=ids,
        # full logits for 2x64x50257 is ~25 MB; keep a dense slice plus
        # global moments — plenty to pin the mapping
        logits_slice=logits[:, :8, :256],
        logits_mean=np.float32(logits.mean()),
        logits_std=np.float32(logits.std()),
        loss=np.float32(jax.device_get(loss)),
    )
    print(f"wrote {args.out}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
