"""Run the queued hardware validation for the round-4 late changes, in
order, with per-phase subprocess timeouts, appending one JSON line per
phase to benchmarks/HW_VALIDATION.jsonl. Safe to re-run: phases are
independent and each line carries its own timestamp-free phase id +
outcome (re-runs append; the newest line for a phase wins).

    python tools/hw_validate.py            # everything
    python tools/hw_validate.py --only compile4k,ab_decode

Phases:
  probe       jax.devices() in a subprocess (bounded) — tunnel health
  compile4k   group_stream fwd+bwd Mosaic compile + finite values,
              T=4096 / 12H / 768C bf16 (124M long-T shape)
  compile32k  same at T=32768 / 4H / 256C (longctx bench shape)
  parity4k    HARDWARE bit-parity: group_stream output vs the unpacked
              streamed family on the same logical q/k/v (the interpret-
              mode assertion, re-proven on real Mosaic lowerings)
  kernel_ab   bench.py --mode kernel --kernel-longt 16384 (A/B: packed
              streamed-group vs unpacked streamed + layout round trip)
  longctx     bench.py --mode longctx (T=32k end-to-end train step;
              round-3 unpacked baseline 101,484 tok/s/chip)
  ab_decode   benchmarks/decode_chunk_ab.py --preset gpt2-small
              (chunked vs monolithic decode, B=1/8/32, one process)
  ab_decode_char  same with --preset char-gpt
  decode_sweep    bench.py --mode decode --preset gpt2-small (the
              RESULTS.md table protocol, post-chunking)
  decode_sweep_packed  same sweep with --decode-cache-layout packed
              (the (L,B,S,C) lane-packed cache A/B, round-5)
  ce_chunk_off/ce_chunk_on  124M train step with the one-shot vs the
              chunked CE head (--loss-chunk 2048) — the giant-vocab
              f32-logits-traffic A/B, round-5
  o200k_vocab_train  100 CLI train iters at vocab 200,064 (the fixed
              o200k configuration's vocab cost, char corpus), round-5

Each phase runs in a fresh subprocess so a hang cannot poison the
orchestrator; the TPU is used by at most one phase at a time.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import signal
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
OUT = ROOT / "benchmarks" / "HW_VALIDATION.jsonl"

COMPILE_SNIPPET = """
import jax, jax.numpy as jnp
from replicatinggpt_tpu.ops.flash_pallas import pallas_flash_attention_packed
T, H, C = {T}, {H}, {C}
qkv = jax.random.normal(jax.random.PRNGKey(0), (1, T, 3 * C), jnp.bfloat16)
f = jax.jit(jax.value_and_grad(lambda q: jnp.sum(
    pallas_flash_attention_packed(q, H, family="group_stream")
    .astype(jnp.float32) ** 2)))
import time; t0 = time.perf_counter()
v, g = f(qkv)
v = float(v)
print("compile+step", round(time.perf_counter() - t0, 1), "s, loss", v,
      "grad-shape", g.shape)
assert v == v and abs(v) < 1e30, "non-finite loss"
assert bool(jnp.isfinite(g.astype(jnp.float32)).all()), "non-finite grads"
print("PASS")
"""

PARITY_SNIPPET = """
import jax, jax.numpy as jnp, numpy as np
from replicatinggpt_tpu.ops.flash_pallas import (
    pallas_flash_attention, pallas_flash_attention_packed)
T, H, D = 4096, 12, 64
C = H * D
qkv = jax.random.normal(jax.random.PRNGKey(1), (1, T, 3 * C), jnp.bfloat16)
got = pallas_flash_attention_packed(qkv, H, family="group_stream")
q, k, v = jnp.split(qkv, 3, -1)
q, k, v = (t.reshape(1, T, H, D).transpose(0, 2, 1, 3) for t in (q, k, v))
ref = pallas_flash_attention(q, k, v)
ref = ref.transpose(0, 2, 1, 3).reshape(1, T, C)
np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
print("PASS bit-equal on hardware")
"""

# bench.py phases: the orchestrator timeout MUST exceed the bench's own
# probe bound (tries x (120s + wait)) + its --watchdog, so bench always
# gets to exit via its graceful watchdog and this process never SIGKILLs
# it mid-TPU-dispatch — a hard kill mid-dispatch is exactly what wedged
# the device claim for 3+ hours (see the verify skill's wedge notes).
# Non-bench phases get generous timeouts for the same reason: only kill
# what is genuinely hung (at which point the device is already stuck).
_BENCH_GUARD = ["--probe-tries", "2", "--probe-wait", "30"]  # <= 300s

PHASES = [
    ("probe", [sys.executable, "-c",
               "import jax; d=jax.devices(); print('ok', d[0].device_kind)"],
     150),
    ("compile4k", [sys.executable, "-c",
                   COMPILE_SNIPPET.format(T=4096, H=12, C=768)], 600),
    ("compile32k", [sys.executable, "-c",
                    COMPILE_SNIPPET.format(T=32768, H=4, C=256)], 900),
    ("parity4k", [sys.executable, "-c", PARITY_SNIPPET], 600),
    ("kernel_ab", [sys.executable, "bench.py", "--mode", "kernel",
                   "--kernel-longt", "16384", "--repeats", "5",
                   "--kernel-inner", "5", "--watchdog", "1200",
                   *_BENCH_GUARD], 1800),
    ("longctx", [sys.executable, "bench.py", "--mode", "longctx",
                 "--watchdog", "1000", *_BENCH_GUARD], 1500),
    ("ab_decode", [sys.executable, "benchmarks/decode_chunk_ab.py",
                   "--preset", "gpt2-small", "--batch-sizes", "1,8,32",
                   "--laps", "5"], 3600),
    ("ab_decode_char", [sys.executable, "benchmarks/decode_chunk_ab.py",
                        "--preset", "char-gpt", "--batch-sizes", "1,8,32",
                        "--laps", "5"], 2400),
    ("decode_sweep", [sys.executable, "bench.py", "--mode", "decode",
                      "--preset", "gpt2-small", "--steps", "5",
                      "--watchdog", "1800", *_BENCH_GUARD], 2400),
    # packed KV-cache layout A/B (round-5): same sweep with the
    # (L, B, S, C) lane-packed cache + the per-layer packed decode
    # kernel; compare against decode_sweep's heads-layout rows
    ("decode_sweep_packed", [sys.executable, "bench.py", "--mode", "decode",
                             "--preset", "gpt2-small", "--steps", "5",
                             "--decode-cache-layout", "packed",
                             "--watchdog", "1800", *_BENCH_GUARD], 2400),
    # chunked-CE head A/B at the giant-vocab train shape (round-5):
    # compare step_ms/mfu against the ce_chunk_off arm in the same queue
    # drain (V=50304 is where the one-shot f32 logits array dominates)
    ("ce_chunk_off", [sys.executable, "bench.py", "--preset", "gpt2-small",
                      "--batch-size", "16", "--steps", "40", "--warmup",
                      "20", "--skip-baseline", "--watchdog", "1200",
                      *_BENCH_GUARD], 1800),
    ("ce_chunk_on", [sys.executable, "bench.py", "--preset", "gpt2-small",
                     "--batch-size", "16", "--steps", "40", "--warmup",
                     "20", "--skip-baseline", "--loss-chunk", "2048",
                     "--watchdog", "1200", *_BENCH_GUARD], 1800),
    # the o200k-CONFIG giant-vocab data point (VERDICT r4 missing #3):
    # the fixed-§8-B1 vocab (200,064 >= o200k's id space) on the char
    # corpus — tiktoken's ranks need network, the vocab cost does not.
    # loss_chunk makes the 13.1 GB one-shot logits array unnecessary.
    ("o200k_vocab_train", [sys.executable, "-m", "replicatinggpt_tpu",
                           "train", "--preset", "char-gpt",
                           "--dataset", "datasets/shakespeare.txt",
                           "--vocab_size", "200064", "--loss-chunk",
                           "2048", "--max-iters", "100",
                           "--eval-interval", "0", "--eval-iters", "20",
                           "--log-interval", "20"], 1800),
]


def run_phase(name: str, cmd, timeout_s: int) -> dict:
    """One phase in a fresh subprocess. On expiry the child gets a
    graceful signal ladder — SIGINT (KeyboardInterrupt: bench watchdogs
    and orbax finalizers run), then SIGTERM, then SIGKILL as the last
    resort — because a SIGKILL mid-TPU-dispatch is exactly the hard-kill
    mode that wedged the axon device claim for hours (comment block
    above). A graceful exit during the ladder still reports
    rc="timeout" — the phase exceeded its budget either way."""
    t0 = time.perf_counter()
    proc = subprocess.Popen(cmd, cwd=ROOT, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    rc = "timeout"
    try:
        out, err = proc.communicate(timeout=timeout_s)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        for sig, grace in ((signal.SIGINT, 120), (signal.SIGTERM, 30)):
            proc.send_signal(sig)
            try:
                out, err = proc.communicate(timeout=grace)
                break
            except subprocess.TimeoutExpired:
                continue
        else:
            proc.kill()
            out, err = proc.communicate()
    # bench progress goes to stderr (log()) — keep both streams. On a
    # timeout keep the full 3000-char window of partial output (where it
    # stalled is the diagnostic); a clean exit only needs the last lines.
    text = ((out or "") + "\n" + (err or "")).strip()
    tail = (text if rc == "timeout"
            else "\n".join(text.splitlines()[-15:]))
    return {"phase": name, "ok": rc == 0, "rc": rc,
            "wall_s": round(time.perf_counter() - t0, 1),
            "tail": tail[-3000:]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default="",
                    help="comma-separated phase names (default: all)")
    ap.add_argument("--stop-on-fail", action="store_true",
                    help="abort the queue on the first failed phase "
                         "(default: continue — later phases may still "
                         "be informative)")
    args = ap.parse_args(argv)
    only = {s for s in args.only.split(",") if s}
    known = {name for name, _, _ in PHASES}
    unknown = only - known
    if unknown:
        ap.error(f"unknown phase(s) {sorted(unknown)}; "
                 f"choose from {sorted(known)}")
    failures = 0
    for name, cmd, timeout_s in PHASES:
        if only and name not in only:
            continue
        print(f"=== {name} (timeout {timeout_s}s)", flush=True)
        rec = run_phase(name, cmd, timeout_s)
        with OUT.open("a") as f:
            f.write(json.dumps(rec) + "\n")
        print(rec["tail"][-800:], flush=True)
        print(f"=== {name}: {'OK' if rec['ok'] else 'FAIL'} "
              f"({rec['wall_s']}s)", flush=True)
        if not rec["ok"]:
            failures += 1
            if name == "probe" or args.stop_on_fail:
                print("aborting queue", flush=True)
                return 1
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
