#!/usr/bin/env python
"""Validate a Chrome trace-event file emitted by utils.telemetry.

The trace is an artifact other tooling (Perfetto, the bench dashboard)
consumes silently — a malformed file renders as an empty timeline, not
an error, so CI validates structure explicitly:

- the file is well-formed JSON with a ``traceEvents`` list;
- duration (B/E) events balance per track with LIFO name matching —
  an unclosed or crossed span renders as garbage nesting;
- complete (X) events carry a non-negative ``dur``;
- every request envelope (a B/E pair named ``request``) opens exactly
  once and closes exactly once per request id, end at-or-after start;
- every span/instant tagged with a request id nests inside that
  request's envelope on the same track (``request_unstarted`` markers
  excepted — a shed/expired request never got a slot or an envelope).

Exits 0 on a valid trace, 1 with one line per violation otherwise.
Used by tests/test_telemetry.py on a tiny replay's output (tier-1) and
by hand on soak artifacts. Stdlib-only on purpose: the validator must
run anywhere the artifact lands, including hosts without jax.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

#: slack for float round-trips at span boundaries (microseconds)
EPS_US = 1.0

#: terminal markers for requests that never got a slot (no envelope)
UNSTARTED = {"request_unstarted"}


def check_trace(path: str, min_requests: int = 0) -> List[str]:
    """Validate one trace file; returns a list of violation strings
    (empty = valid)."""
    errors: List[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable trace JSON: {e}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["no traceEvents list"]

    stacks: Dict[Tuple[int, int], List[dict]] = {}
    # request id -> (tid, ts_begin, ts_end or None, n_begin, n_end)
    envelopes: Dict[str, dict] = {}
    tagged: List[dict] = []

    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            continue
        key = (ev.get("pid", 0), ev.get("tid", 0))
        name = ev.get("name", "")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            errors.append(f"{ph} event {name!r} has no numeric ts")
            continue
        rid = (ev.get("args") or {}).get("request")
        if ph == "B":
            stacks.setdefault(key, []).append(ev)
            if name == "request":
                env = envelopes.setdefault(
                    rid, {"tid": key, "b": ts, "e": None,
                          "n_b": 0, "n_e": 0})
                env["n_b"] += 1
                env["b"] = ts
                env["tid"] = key
        elif ph == "E":
            stack = stacks.get(key, [])
            if not stack:
                errors.append(f"E {name!r} on track {key} with no open B")
            else:
                top = stack.pop()
                if top.get("name") != name:
                    errors.append(
                        f"E {name!r} closes B {top.get('name')!r} on "
                        f"track {key} (crossed spans)")
            if name == "request":
                env = envelopes.setdefault(
                    rid, {"tid": key, "b": None, "e": ts,
                          "n_b": 0, "n_e": 0})
                env["n_e"] += 1
                env["e"] = ts
        elif ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"X {name!r} has bad dur {dur!r}")
            elif rid is not None:
                tagged.append(ev)
        elif ph == "i":
            if rid is not None and name not in UNSTARTED:
                tagged.append(ev)

    for key, stack in stacks.items():
        for ev in stack:
            errors.append(f"B {ev.get('name')!r} on track {key} never "
                          f"closed")

    n_complete = 0
    for rid, env in sorted(envelopes.items(), key=lambda kv: str(kv[0])):
        if env["n_b"] != 1 or env["n_e"] != 1:
            errors.append(f"request {rid!r}: {env['n_b']} B / "
                          f"{env['n_e']} E envelope events (want 1/1)")
            continue
        if env["e"] < env["b"] - EPS_US:
            errors.append(f"request {rid!r}: envelope ends before it "
                          f"begins ({env['e']} < {env['b']})")
            continue
        n_complete += 1

    for ev in tagged:
        rid = ev["args"]["request"]
        env = envelopes.get(rid)
        name = ev.get("name")
        if env is None or env["b"] is None or env["e"] is None:
            errors.append(f"{ev['ph']} {name!r} tagged request {rid!r} "
                          f"which has no complete envelope")
            continue
        key = (ev.get("pid", 0), ev.get("tid", 0))
        if key != env["tid"]:
            errors.append(f"{ev['ph']} {name!r} for request {rid!r} on "
                          f"track {key}, envelope on {env['tid']}")
            continue
        lo = ev["ts"]
        hi = lo + ev.get("dur", 0.0)
        if lo < env["b"] - EPS_US or hi > env["e"] + EPS_US:
            errors.append(
                f"{ev['ph']} {name!r} for request {rid!r} "
                f"[{lo:.1f}, {hi:.1f}] outside its envelope "
                f"[{env['b']:.1f}, {env['e']:.1f}]")

    if n_complete < min_requests:
        errors.append(f"only {n_complete} complete request envelope(s); "
                      f"expected >= {min_requests}")
    return errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="validate a utils.telemetry Chrome trace file")
    p.add_argument("trace", help="path to the trace JSON")
    p.add_argument("--min-requests", type=int, default=0,
                   help="fail unless at least this many complete "
                        "request span trees are present")
    args = p.parse_args(argv)
    errors = check_trace(args.trace, min_requests=args.min_requests)
    for e in errors:
        print(f"trace_check: {e}", file=sys.stderr)
    if not errors:
        print(f"trace_check: {args.trace} OK")
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
