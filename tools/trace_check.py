#!/usr/bin/env python
"""Validate a Chrome trace-event file emitted by utils.telemetry.

The trace is an artifact other tooling (Perfetto, the bench dashboard)
consumes silently — a malformed file renders as an empty timeline, not
an error, so CI validates structure explicitly:

- the file is well-formed JSON with a ``traceEvents`` list;
- duration (B/E) events balance per track with LIFO name matching —
  an unclosed or crossed span renders as garbage nesting;
- complete (X) events carry a non-negative ``dur``;
- request envelopes (B/E pairs named ``request``) form **exactly one
  complete span tree per request id**. A request that migrated
  replicas (fleet router requeue / hedged re-route) closes its old
  segment with an E tagged ``migrated`` — those are non-terminal
  segments; every request must have exactly ONE terminal (unmigrated)
  close, each segment must end at-or-after it begins, and no segment
  may be left open;
- every span/instant tagged with a request id nests inside one of that
  request's envelope segments on the same track
  (``request_unstarted`` markers excepted — a shed/expired request
  never got a slot or an envelope — and so is everything on a
  **router track**: the router observes requests from outside their
  slot lifetime, so its route/requeue/health instants legitimately
  fall outside any envelope). Router tracks are recognized by their
  thread-name metadata (``utils.telemetry.ROUTER_TRACK_NAME``) so
  this validator stays stdlib-only with no imports from the package;
- disaggregated prefill/decode requests (serve/disagg.py) are checked
  against the fleet-wide envelope: a ``page_transfer`` X span on the
  router track names the request it moves pages for, and must fall
  inside that request's envelope HULL — at-or-after its earliest
  segment opens (the prefill tier's half, which closes ``migrated``
  before the transfer starts) and at-or-before its terminal segment
  closes (the decode tier's half). A transfer for a request with no
  envelope, or one dangling past the terminal close, means the router
  shipped pages for a request it no longer owns. The
  exactly-one-terminal-close rule above is what "a disaggregated
  request's envelope closes exactly once fleet-wide" means: prefill
  segment migrated, decode segment terminal;
- multi-token decode windows are allowed and checked: a window's
  ``decode``/``verify`` X span may contain MANY per-request ``token``
  instants; each must carry a positive integer ``index`` (the
  request's running token count) and a request's token indices must be
  strictly increasing in event order WITHIN an envelope segment — a
  duplicate or backwards index means the async engine double-delivered
  or dropped part of a window. The floor resets when a new envelope
  segment opens (a migrated / journal-replayed request re-decodes from
  token 0 on its new replica; the delivery ledger, not the trace,
  dedupes the client stream), and the ring buffer may evict the oldest
  events, so indices need not start at 1.

Exits 0 on a valid trace, 1 with one line per violation otherwise.
Used by tests/test_telemetry.py on a tiny replay's output (tier-1), by
tests/test_fleet.py on a replica-kill chaos replay's output, and by
hand on soak artifacts. Stdlib-only on purpose: the validator must run
anywhere the artifact lands, including hosts without jax.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

#: slack for float round-trips at span boundaries (microseconds)
EPS_US = 1.0

#: terminal markers for requests that never got a slot (no envelope)
UNSTARTED = {"request_unstarted"}

#: thread-name metadata marking the fleet router's track — events there
#: are envelope-exempt (must match utils.telemetry.ROUTER_TRACK_NAME)
ROUTER_TRACK_NAME = "router"

#: Every event name this validator's logic keys on. graftlint GL023
#: holds each entry against an actual emission site (a ``t.begin(...)``
#: / ``t.instant(...)`` / thread_name metadata literal somewhere in the
#: tree) — a span renamed at the emitter without updating the validator
#: silently stops validating that lifecycle edge.
TRACE_VALIDATED_NAMES = ("request", "page_transfer", "token",
                         "request_unstarted", ROUTER_TRACK_NAME,
                         "thread_name", "net_partition", "net_heal")


def check_trace(path: str, min_requests: int = 0) -> List[str]:
    """Validate one trace file; returns a list of violation strings
    (empty = valid)."""
    errors: List[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable trace JSON: {e}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["no traceEvents list"]

    # first pass: which tracks are router tracks (by thread_name meta)
    router_tracks = set()
    for ev in events:
        if (ev.get("ph") == "M" and ev.get("name") == "thread_name"
                and (ev.get("args") or {}).get("name")
                == ROUTER_TRACK_NAME):
            router_tracks.add((ev.get("pid", 0), ev.get("tid", 0)))

    stacks: Dict[Tuple[int, int], List[dict]] = {}
    # request id -> closed envelope segments
    # [{"tid", "b", "e", "migrated"}]; open segments keyed (rid, track)
    segments: Dict[str, List[dict]] = {}
    open_envs: Dict[Tuple[str, Tuple[int, int]], List[float]] = {}
    tagged: List[dict] = []
    # router-track page_transfer X spans (disaggregation): checked
    # against the request's fleet-wide envelope hull, not one segment
    transfers: List[dict] = []
    # request id -> highest token-instant index seen (window deliveries)
    token_indices: Dict[str, int] = {}
    # replica -> currently-open net_partition count (netchaos edges:
    # every heal must match an earlier partition on the same replica)
    net_open: Dict[object, int] = {}

    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            continue
        key = (ev.get("pid", 0), ev.get("tid", 0))
        name = ev.get("name", "")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            errors.append(f"{ph} event {name!r} has no numeric ts")
            continue
        args = ev.get("args") or {}
        rid = args.get("request")
        on_router = key in router_tracks
        if ph == "B":
            stacks.setdefault(key, []).append(ev)
            if name == "request":
                open_envs.setdefault((rid, key), []).append(ts)
                # a fresh envelope segment (re-admission after a
                # migration / journal replay) legitimately re-decodes
                # from token 0 — the index floor resets per segment
                token_indices.pop(rid, None)
        elif ph == "E":
            stack = stacks.get(key, [])
            if not stack:
                errors.append(f"E {name!r} on track {key} with no open B")
            else:
                top = stack.pop()
                if top.get("name") != name:
                    errors.append(
                        f"E {name!r} closes B {top.get('name')!r} on "
                        f"track {key} (crossed spans)")
            if name == "request":
                opened = open_envs.get((rid, key))
                if not opened:
                    errors.append(f"request {rid!r}: E envelope on "
                                  f"track {key} with no open B")
                    continue
                b = opened.pop()
                segments.setdefault(rid, []).append(
                    {"tid": key, "b": b, "e": ts,
                     "migrated": bool(args.get("migrated"))})
        elif ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"X {name!r} has bad dur {dur!r}")
            elif rid is not None and not on_router:
                tagged.append(ev)
            elif rid is not None and name == "page_transfer":
                transfers.append(ev)
        elif ph == "i":
            if name in ("net_partition", "net_heal"):
                if not on_router:
                    errors.append(f"{name} instant off the router "
                                  f"track (track {key})")
                rep = args.get("replica")
                if name == "net_partition":
                    net_open[rep] = net_open.get(rep, 0) + 1
                elif net_open.get(rep, 0) <= 0:
                    errors.append(f"net_heal for replica {rep!r} with "
                                  f"no open net_partition")
                else:
                    net_open[rep] -= 1
            if rid is not None and name not in UNSTARTED and not on_router:
                tagged.append(ev)
                if name == "token":
                    idx = args.get("index")
                    if not isinstance(idx, int) or isinstance(idx, bool) \
                            or idx < 1:
                        errors.append(
                            f"token instant for request {rid!r} has bad "
                            f"index {idx!r} (want int >= 1)")
                    else:
                        prev = token_indices.get(rid)
                        if prev is not None and idx <= prev:
                            errors.append(
                                f"request {rid!r}: token index {idx} "
                                f"after {prev} (token instants must be "
                                f"strictly increasing — duplicate or "
                                f"reordered window delivery)")
                        token_indices[rid] = (idx if prev is None
                                              else max(prev, idx))

    for key, stack in stacks.items():
        for ev in stack:
            errors.append(f"B {ev.get('name')!r} on track {key} never "
                          f"closed")
    for (rid, key), opened in open_envs.items():
        for _ in opened:
            errors.append(f"request {rid!r}: envelope segment on track "
                          f"{key} never closed")

    n_complete = 0
    for rid, segs in sorted(segments.items(), key=lambda kv: str(kv[0])):
        bad = False
        for seg in segs:
            if seg["e"] < seg["b"] - EPS_US:
                errors.append(
                    f"request {rid!r}: envelope segment on track "
                    f"{seg['tid']} ends before it begins "
                    f"({seg['e']} < {seg['b']})")
                bad = True
        terminal = [s for s in segs if not s["migrated"]]
        if len(terminal) != 1:
            errors.append(
                f"request {rid!r}: {len(terminal)} terminal envelope "
                f"segment(s) across {len(segs)} segment(s) (want "
                f"exactly 1 — migrated segments must carry the "
                f"'migrated' tag)")
            bad = True
        if not bad:
            n_complete += 1

    for ev in tagged:
        rid = ev["args"]["request"]
        segs = segments.get(rid)
        name = ev.get("name")
        if not segs:
            errors.append(f"{ev['ph']} {name!r} tagged request {rid!r} "
                          f"which has no complete envelope")
            continue
        key = (ev.get("pid", 0), ev.get("tid", 0))
        lo = ev["ts"]
        hi = lo + ev.get("dur", 0.0)
        if not any(seg["tid"] == key
                   and lo >= seg["b"] - EPS_US
                   and hi <= seg["e"] + EPS_US for seg in segs):
            errors.append(
                f"{ev['ph']} {name!r} for request {rid!r} "
                f"[{lo:.1f}, {hi:.1f}] on track {key} outside every "
                f"envelope segment of that request")

    for ev in transfers:
        rid = ev["args"]["request"]
        segs = segments.get(rid)
        lo = ev["ts"]
        hi = lo + ev.get("dur", 0.0)
        if not segs:
            errors.append(f"page_transfer for request {rid!r} which has "
                          f"no complete envelope (pages shipped for a "
                          f"request the fleet never ran)")
            continue
        hull_lo = min(s["b"] for s in segs)
        hull_hi = max(s["e"] for s in segs)
        if lo < hull_lo - EPS_US or hi > hull_hi + EPS_US:
            errors.append(
                f"page_transfer for request {rid!r} [{lo:.1f}, {hi:.1f}] "
                f"outside its fleet-wide envelope hull "
                f"[{hull_lo:.1f}, {hull_hi:.1f}] — the router moved "
                f"pages for a request it no longer owned")
            continue
        if not any(s["migrated"] and s["b"] <= lo + EPS_US
                   for s in segs):
            errors.append(
                f"page_transfer for request {rid!r} with no migrated "
                f"(prefill-tier) envelope segment opened before it — "
                f"a transfer must follow a diverted prefill")

    if n_complete < min_requests:
        errors.append(f"only {n_complete} complete request envelope(s); "
                      f"expected >= {min_requests}")
    return errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="validate a utils.telemetry Chrome trace file")
    p.add_argument("trace", help="path to the trace JSON")
    p.add_argument("--min-requests", type=int, default=0,
                   help="fail unless at least this many complete "
                        "request span trees are present")
    args = p.parse_args(argv)
    errors = check_trace(args.trace, min_requests=args.min_requests)
    for e in errors:
        print(f"trace_check: {e}", file=sys.stderr)
    if not errors:
        print(f"trace_check: {args.trace} OK")
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
