#!/bin/bash
# Drain the queued hardware work after tunnel recovery, in VERDICT r4
# priority order: (1) tools/hw_validate.py (13 phases incl. the
# group_stream compile/parity gates, decode layout + CE-chunk A/Bs,
# o200k vocab run), (2) driver-default bench.py, (3) the gpt2-large
# 774M 500-step single-chip training run. One TPU process at a time;
# graceful signals only (SIGKILL mid-dispatch wedges the tunnel).
set -u
cd /root/repo
LOG=benchmarks/hw_drain.log
echo "=== drain start $(date -u +%FT%TZ)" >> "$LOG"
python tools/hw_validate.py >> "$LOG" 2>&1
echo "=== hw_validate rc=$? $(date -u +%FT%TZ)" >> "$LOG"
timeout -s INT --kill-after=60 2400 python bench.py \
  > benchmarks/BENCH_r05_builder.json 2>> "$LOG"
echo "=== bench rc=$? $(date -u +%FT%TZ)" >> "$LOG"
# continuous-window serve row (ISSUE 13): dispatch split at window k=8
# + admission-storm retention + autotuned k, 1x1 then the 2x2 mesh
timeout -s INT --kill-after=60 1800 python bench.py --mode serve \
  --decode-window 8 --decode-window-auto --serve-storm-trace \
  > benchmarks/BENCH_serve_window.json 2>> "$LOG"
echo "=== serve-window rc=$? $(date -u +%FT%TZ)" >> "$LOG"
timeout -s INT --kill-after=60 1800 python bench.py --mode serve \
  --decode-window 8 --decode-window-auto --serve-storm-trace \
  --mesh-shape 2x2 \
  > benchmarks/BENCH_serve_window_2x2.json 2>> "$LOG"
echo "=== serve-window-2x2 rc=$? $(date -u +%FT%TZ)" >> "$LOG"
# quantized-serving rows (ISSUE 15): int8 paged KV on the shared-
# prefix trace at 1x1 and 2x2, plus the bf16-vs-int8 fixed-HBM
# capacity/divergence A/B (quant_ab artifact block)
timeout -s INT --kill-after=60 1800 python bench.py --mode serve \
  --kv-quant int8 --serve-prefix-trace \
  > benchmarks/BENCH_serve_quant.json 2>> "$LOG"
echo "=== serve-quant rc=$? $(date -u +%FT%TZ)" >> "$LOG"
timeout -s INT --kill-after=60 1800 python bench.py --mode serve \
  --kv-quant int8 --serve-prefix-trace --mesh-shape 2x2 \
  > benchmarks/BENCH_serve_quant_2x2.json 2>> "$LOG"
echo "=== serve-quant-2x2 rc=$? $(date -u +%FT%TZ)" >> "$LOG"
timeout -s INT --kill-after=60 1800 python bench.py --mode serve \
  --quant-ab --serve-prefix-trace \
  > benchmarks/BENCH_serve_quant_ab.json 2>> "$LOG"
echo "=== serve-quant-ab rc=$? $(date -u +%FT%TZ)" >> "$LOG"
# elastic-fleet rows (ISSUE 14): host_loss chaos mid-run (journal +
# workdir deleted, router-ledger recovery) and the autoscaler
# load-step preset (scale-up/scale-down with zero drops)
timeout -s INT --kill-after=60 1800 python bench.py --mode fleet \
  --multiproc --fleet-replicas 2 --fleet-kill-at 60 --fleet-host-loss \
  > benchmarks/BENCH_fleet_host_loss.json 2>> "$LOG"
echo "=== fleet-host-loss rc=$? $(date -u +%FT%TZ)" >> "$LOG"
timeout -s INT --kill-after=60 1800 python bench.py --mode fleet \
  --fleet-load-step --fleet-replicas 3 \
  > benchmarks/BENCH_fleet_load_step.json 2>> "$LOG"
echo "=== fleet-load-step rc=$? $(date -u +%FT%TZ)" >> "$LOG"
# disaggregation rows (ISSUE 16): colocated-vs-tiered TTFT A/B at
# equal worker count (disagg_ab artifact block: short/long TTFT
# p50/p99 both arms, transfer counters + p99, token-identity bit) —
# bf16 pool and int8 paged KV (quantized pages + scales on the wire)
timeout -s INT --kill-after=60 1800 python bench.py --mode fleet \
  --disagg --fleet-replicas 4 \
  > benchmarks/BENCH_fleet_disagg_ab.json 2>> "$LOG"
echo "=== fleet-disagg-ab rc=$? $(date -u +%FT%TZ)" >> "$LOG"
timeout -s INT --kill-after=60 1800 python bench.py --mode fleet \
  --disagg --fleet-replicas 4 --kv-quant int8 \
  > benchmarks/BENCH_fleet_disagg_ab_int8.json 2>> "$LOG"
echo "=== fleet-disagg-ab-int8 rc=$? $(date -u +%FT%TZ)" >> "$LOG"
# unified-kernel rows (ISSUE 20): every shipped config through the ONE
# Pallas kernel family — the artifact's kernel_route block must read
# route == "pallas" with empty reasons on each row
timeout -s INT --kill-after=60 1800 python bench.py --mode serve \
  --paged-kernel --kv-quant int8 --serve-storm-trace \
  > benchmarks/BENCH_serve_kernel_1x1.json 2>> "$LOG"
echo "=== serve-kernel-1x1 rc=$? $(date -u +%FT%TZ)" >> "$LOG"
timeout -s INT --kill-after=60 1800 python bench.py --mode serve \
  --paged-kernel --kv-quant int8 --mesh-shape 2x2 --serve-storm-trace \
  > benchmarks/BENCH_serve_kernel_2x2.json 2>> "$LOG"
echo "=== serve-kernel-2x2 rc=$? $(date -u +%FT%TZ)" >> "$LOG"
timeout -s INT --kill-after=60 1800 python bench.py --mode serve \
  --paged-kernel --kv-quant int8 --quant-granularity head \
  --serve-prefix-trace \
  > benchmarks/BENCH_serve_kernel_headgran.json 2>> "$LOG"
echo "=== serve-kernel-headgran rc=$? $(date -u +%FT%TZ)" >> "$LOG"
mkdir -p benchmarks/converged_gpt2
timeout -s INT --kill-after=60 5400 python -m replicatinggpt_tpu train \
  --preset gpt2-large --dataset datasets/shakespeare.txt \
  --batch-size 8 --max-iters 500 --eval-interval 0 --eval-iters 20 \
  --log-interval 20 \
  --log-jsonl benchmarks/converged_gpt2/gpt2_large_500.jsonl \
  >> "$LOG" 2>&1
echo "=== gpt2-large rc=$? $(date -u +%FT%TZ)" >> "$LOG"
echo "=== drain done $(date -u +%FT%TZ)" >> "$LOG"
