#!/bin/bash
# Probe loop: check TPU backend availability every 5 min, log to benchmarks/tpu_probe.log.
# Exits 0 as soon as a probe succeeds.
LOG=/root/repo/benchmarks/tpu_probe.log
for i in $(seq 1 120); do
  TS=$(date -u +%FT%TZ)
  if timeout -s INT --kill-after=30 120 python -c "import jax; d=jax.devices(); print(d)" >>"$LOG" 2>&1; then
    echo "$TS probe $i: OK" >> "$LOG"
    exit 0
  else
    echo "$TS probe $i: timeout/fail" >> "$LOG"
  fi
  sleep 300
done
echo "gave up after 120 probes" >> "$LOG"
exit 1
