#!/usr/bin/env python
"""Benchmark harness: char-GPT train tokens/sec/chip.

Runs the BASELINE.json parity workload (char-GPT: 6L/6H/384C, block 256,
batch 64 — BASELINE.md config 1/2) as jitted bf16 train steps on the
available accelerator and reports steady-state throughput.

vs_baseline is the ratio against the PyTorch-CPU reference path
(replicatinggpt_tpu/reference_torch.py) on this machine — the BASELINE.md
target is >50x ("reach reference loss in <1/50 wall-clock", and step time
dominates wall-clock at fixed iteration count). The CPU measurement is
cached in BENCH_BASELINE_CACHE.json so repeated bench runs don't re-pay it.

Prints exactly ONE JSON line to stdout; all narration goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_BASELINE_CACHE.json")


def torch_cpu_baseline(mcfg, batch_size: int, remeasure: bool) -> float:
    key = (f"char_gpt_L{mcfg.n_layer}_H{mcfg.n_head}_C{mcfg.n_embd}"
           f"_T{mcfg.block_size}_B{batch_size}")
    cache = {}
    if os.path.exists(CACHE_PATH):
        try:
            with open(CACHE_PATH) as f:
                cache = json.load(f)
        except Exception:
            cache = {}
    if not remeasure and key in cache:
        log(f"torch-CPU baseline (cached): {cache[key]:,.0f} tok/s")
        return cache[key]
    log("measuring torch-CPU reference baseline (few steps)...")
    import torch

    from replicatinggpt_tpu.reference_torch import measure_train_throughput
    torch.set_num_threads(os.cpu_count() or 8)
    tps = measure_train_throughput(mcfg, batch_size=batch_size, steps=3,
                                   warmup=1)
    cache[key] = tps
    try:
        with open(CACHE_PATH, "w") as f:
            json.dump(cache, f, indent=1)
    except OSError:
        pass
    log(f"torch-CPU baseline: {tps:,.0f} tok/s")
    return tps


def bench_generate(args) -> None:
    """BASELINE.json config 5: autoregressive generate latency — 1k-token
    sample, p50 tokens/sec — measured with the blocking StepTimer
    discipline (one lap per 256-token decode segment)."""
    import jax
    import jax.numpy as jnp

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.sample import GenerateConfig, generate
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.utils.profiling import StepTimer

    cfg = get_config(args.preset)
    mcfg = cfg.model
    state = create_train_state(jax.random.PRNGKey(0), mcfg, cfg.train)
    gcfg = GenerateConfig(max_new_tokens=1000, top_k=50)
    prompt = jnp.zeros((1, 1), jnp.int32)
    log(f"generate bench: 1000 tokens, top-k 50, "
        f"{mcfg.n_layer}L/{mcfg.n_head}H/{mcfg.n_embd}C")
    jax.block_until_ready(generate(state.params, prompt, mcfg, gcfg))  # warm
    timer = StepTimer()
    timer.start()
    for i in range(args.steps):
        toks = generate(state.params, prompt, mcfg, gcfg,
                        rng=jax.random.PRNGKey(i))
        timer.lap(toks)
    s = timer.summary(tokens_per_step=gcfg.max_new_tokens)
    log(f"p50 {s['p50_s'] * 1e3:.1f} ms/1k-tok, "
        f"{s['tokens_per_sec_per_chip']:,.0f} tok/s p50")
    print(json.dumps({
        "metric": "generate_1k_tokens_per_sec_p50",
        "value": round(s["tokens_per_sec_per_chip"], 1),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,  # reference publishes no generation numbers
    }))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="char-gpt")
    p.add_argument("--mode", default="train", choices=["train", "generate"])
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--steps-per-dispatch", type=int, default=25,
                   help="lax.scan K optimizer steps per device dispatch "
                        "(amortizes host->device round-trip latency, which "
                        "dominates small-model step time on tunneled TPUs)")
    p.add_argument("--rng-impl", default="rbg",
                   choices=["threefry2x32", "rbg"],
                   help="dropout PRNG; rbg uses the TPU hardware generator "
                        "(~15%% faster steps at dropout 0.2; same mask "
                        "distribution, different bits than threefry)")
    p.add_argument("--remeasure-baseline", action="store_true")
    p.add_argument("--skip-baseline", action="store_true",
                   help="report vs_baseline from cache or 0 if absent")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. 'cpu'); note the "
                        "JAX_PLATFORMS env var is overridden by PJRT "
                        "plugins in some environments — this flag uses "
                        "jax.config, which always wins")
    args = p.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    jax.config.update("jax_default_prng_impl", args.rng_impl)
    if args.mode == "generate":
        return bench_generate(args)
    import numpy as np

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.data.dataset import TokenDataset, load_corpus
    from replicatinggpt_tpu.data.loader import RandomBatcher, prefetch
    from replicatinggpt_tpu.tokenizers import get_tokenizer
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.train.steps import (make_train_scan,
                                                make_train_step)

    cfg = get_config(args.preset)
    mcfg, tcfg = cfg.model, cfg.train
    B, T = args.batch_size, mcfg.block_size
    dev = jax.devices()[0]
    log(f"benchmark device: {dev.platform} ({dev.device_kind}), "
        f"model {mcfg.n_layer}L/{mcfg.n_head}H/{mcfg.n_embd}C "
        f"T={T} B={B} dtype={mcfg.dtype}")

    # real input pipeline: tokenized Tiny Shakespeare, random windows
    text = load_corpus(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    cfg.dataset))
    tok = get_tokenizer(cfg.tokenizer, corpus_text=text)
    ds = TokenDataset.from_text(text, tok, tcfg.val_fraction)
    batcher = RandomBatcher(ds.train, B, T, seed=tcfg.seed)

    state = create_train_state(jax.random.PRNGKey(tcfg.seed), mcfg, tcfg)
    k = max(args.steps_per_dispatch, 1)
    # narrow transfer dtype: token ids fit uint8/uint16 for every preset
    # vocab; 2-4x less H2D traffic (the tunnel's bandwidth is precious),
    # widened to int32 on device inside the jitted step (steps.loss_fn)
    wire = (np.uint8 if mcfg.vocab_size <= 0xff
            else np.uint16 if mcfg.vocab_size <= 0xffff else np.int32)
    if k > 1:
        run = make_train_scan(mcfg, tcfg, k)
        def stacked():
            xs, ys = zip(*(batcher.next_batch() for _ in range(k)))
            return np.stack(xs).astype(wire), np.stack(ys).astype(wire)
        batches = prefetch(iter(stacked, None), depth=2)
    else:
        run = make_train_step(mcfg, tcfg)
        batches = prefetch(iter(batcher), depth=2)
    # round the requested counts UP to whole dispatches and report what
    # actually runs (tps is computed over the actual count either way)
    n_dispatch = -(-args.steps // k)
    n_warmup = -(-args.warmup // k) if args.warmup > 0 else 0
    if (n_dispatch * k, n_warmup * k) != (args.steps, args.warmup):
        log(f"note: measuring {n_dispatch * k} steps / warming up "
            f"{n_warmup * k} (rounded up to whole {k}-step dispatches)")

    log(f"compiling... ({k} steps/dispatch)")
    t0 = time.perf_counter()
    for _ in range(n_warmup):
        state, metrics = run(state, next(batches))
    jax.block_until_ready(metrics["loss"])
    log(f"warmup done in {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    for _ in range(n_dispatch):
        state, metrics = run(state, next(batches))
    loss = float(np.asarray(jax.device_get(metrics["loss"])).ravel()[-1])
    dt = time.perf_counter() - t0
    tps = B * T * n_dispatch * k / dt
    log(f"{n_dispatch * k} steps in {dt:.2f}s -> {tps:,.0f} tok/s/chip, "
        f"loss {loss:.4f}")
    assert np.isfinite(loss)

    if args.skip_baseline:
        base = 0.0
        if os.path.exists(CACHE_PATH):
            try:
                with open(CACHE_PATH) as f:
                    base = list(json.load(f).values())[0]
            except Exception:
                base = 0.0
    else:
        base = torch_cpu_baseline(mcfg, B, args.remeasure_baseline)

    print(json.dumps({
        "metric": "char_gpt_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps / base, 2) if base > 0 else 0.0,
    }))


if __name__ == "__main__":
    main()
