#!/usr/bin/env python
"""Benchmark harness: char-GPT train tokens/sec/chip (+ MFU, + generate p50).

Runs the BASELINE.json parity workload (char-GPT: 6L/6H/384C, block 256,
batch 64 — BASELINE.md config 1/2) as jitted bf16 train steps on the
available accelerator and reports steady-state throughput.

vs_baseline is the ratio against the PyTorch-CPU reference path
(replicatinggpt_tpu/reference_torch.py) on this machine — the BASELINE.md
target is >50x ("reach reference loss in <1/50 wall-clock", and step time
dominates wall-clock at fixed iteration count). The CPU measurement is
cached in BENCH_BASELINE_CACHE.json so repeated bench runs don't re-pay it.

Robustness contract (the driver keeps exactly one artifact per round):
- prints exactly ONE JSON line to stdout, ALWAYS — on any failure the line
  carries an "error" field instead of silently dying with rc!=0/no output;
- backend init is probed in a subprocess with bounded retries (the tunneled
  TPU backend wedges transiently, and a wedged init hangs the caller);
- a watchdog thread emits the JSON line and exits if the whole run exceeds
  its budget (mid-run device hangs can't swallow the artifact either).

Self-auditing: the JSON line includes an analytic FLOPs model (see
train_flops_per_token) and the resulting MFU against the device's bf16
peak, plus the dispatch/compute split, so the throughput number can be
sanity-checked at a glance.

All narration goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_BASELINE_CACHE.json")

_EMIT_LOCK = threading.Lock()
_EMITTED = False
# tags merged into the artifact by emit() — e.g. {"backend":
# "cpu-fallback"} when the accelerator probe gave up and the run
# proceeded on CPU (a tagged measurement beats a zero-valued error line)
_EMIT_TAGS: dict = {}


def emit(payload: dict) -> None:
    """Print the single JSON artifact line (first caller wins)."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return
        _EMITTED = True
    if os.environ.get("GRAFT_SANITIZE", "") not in ("", "0"):
        # sanitized runs pay for leak/NaN checks — never comparable to
        # (or mistakable for) a real measurement
        payload = {**payload, "sanitize": True}
    if _EMIT_TAGS:
        payload = {**payload, **_EMIT_TAGS}
    print(json.dumps(payload), flush=True)


def error_payload(metric: str, unit: str, err: str) -> dict:
    return {"metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "error": err[:500]}


def start_watchdog(seconds: float, metric: str, unit: str) -> None:
    """Emit an error artifact and hard-exit if the run outlives its budget.

    os._exit (not sys.exit) because the typical cause is a thread wedged
    inside a PJRT call that will never return or honor interpreters exits.
    """
    def fire():
        time.sleep(seconds)
        log(f"WATCHDOG: bench exceeded {seconds:.0f}s budget; emitting "
            "error artifact and exiting")
        emit(error_payload(metric, unit,
                           f"watchdog: exceeded {seconds:.0f}s budget "
                           "(device hang?)"))
        sys.stdout.flush()
        os._exit(3)

    t = threading.Thread(target=fire, daemon=True)
    t.start()


def probe_backend(platform: str | None, tries: int, wait_s: float) -> None:
    """Check backend init completes in a subprocess before touching it here.

    The axon TPU tunnel wedges transiently — even ``jax.devices()`` can
    block forever, and a backend that failed init once poisons the calling
    process. Probing in a throwaway subprocess (with a hard timeout) keeps
    this process clean across retries. Raises after the last failure.
    """
    force = (f"jax.config.update('jax_platforms', {platform!r}); "
             if platform else "")
    code = (f"import jax; {force}d = jax.devices(); "
            f"print(d[0].platform, d[0].device_kind)")
    last = "unknown"
    for i in range(tries):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True, timeout=120)
            if r.returncode == 0:
                log(f"backend probe ok: {r.stdout.strip()}")
                return
            last = (r.stderr.strip() or "nonzero rc").splitlines()[-1]
        except subprocess.TimeoutExpired:
            last = "probe timed out after 120s (wedged tunnel?)"
        if i < tries - 1:
            log(f"backend probe {i + 1}/{tries} failed ({last}); "
                f"retrying in {wait_s:.0f}s")
            time.sleep(wait_s)
    raise RuntimeError(f"backend unavailable after {tries} probes: {last}")


def train_flops_per_token(mcfg) -> float:
    """Analytic training FLOPs per token (matmul terms only; the standard
    MFU accounting — layernorm/softmax/embedding-gather excluded).

    Per layer the matmul weights are qkv 3d^2 + attn-proj d^2 + mlp 8d^2
    = 12d^2; the lm_head matmul is d*V (counted tied or not — tying shares
    storage, not FLOPs). Forward = 2 FLOPs/param-use; backward = 2x
    forward; attention scores+values add 4dT FLOPs/token/layer forward,
    halved by causal masking.
    """
    L, d, T, V = (mcfg.n_layer, mcfg.n_embd, mcfg.block_size,
                  mcfg.vocab_size)
    fwd_matmul = 2.0 * (12.0 * L * d * d + d * V)
    fwd_attn = 2.0 * L * d * T  # 4dT full, /2 causal
    return 3.0 * (fwd_matmul + fwd_attn)


# bf16 dense peak FLOPs/s per chip by device_kind substring (MXU peak;
# public cloud.google.com/tpu/docs numbers)
_PEAK_FLOPS = [
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v6", 918e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]


def peak_flops_per_sec(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


def _baseline_key(mcfg, batch_size: int) -> str:
    return (f"char_gpt_L{mcfg.n_layer}_H{mcfg.n_head}_C{mcfg.n_embd}"
            f"_T{mcfg.block_size}_B{batch_size}")


def torch_cpu_baseline(mcfg, batch_size: int, remeasure: bool) -> float:
    key = _baseline_key(mcfg, batch_size)
    cache = {}
    if os.path.exists(CACHE_PATH):
        try:
            with open(CACHE_PATH) as f:
                cache = json.load(f)
        except (OSError, ValueError):   # unreadable/corrupt cache: remeasure
            cache = {}
    if not remeasure and key in cache:
        log(f"torch-CPU baseline (cached): {cache[key]:,.0f} tok/s")
        return cache[key]
    log("measuring torch-CPU reference baseline (few steps)...")
    import torch

    from replicatinggpt_tpu.reference_torch import measure_train_throughput
    torch.set_num_threads(os.cpu_count() or 8)
    tps = measure_train_throughput(mcfg, batch_size=batch_size, steps=3,
                                   warmup=1)
    cache[key] = tps
    try:
        with open(CACHE_PATH, "w") as f:
            json.dump(cache, f, indent=1)
    except OSError:
        pass
    log(f"torch-CPU baseline: {tps:,.0f} tok/s")
    return tps


def measure_generate_p50(mcfg, tcfg, steps: int = 4,
                         batch_size: int = 1, state=None) -> dict:
    """BASELINE.json config 5: autoregressive generate latency — 1k-token
    sample, p50 tokens/sec — with real device->host fetch per lap.
    ``batch_size`` > 1 measures batched decode (aggregate throughput =
    B * 1000 / p50); pass ``state`` to reuse one model across a sweep."""
    import jax
    import jax.numpy as jnp

    from replicatinggpt_tpu.sample import GenerateConfig, generate
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.utils.profiling import StepTimer

    if state is None:
        state = create_train_state(jax.random.PRNGKey(0), mcfg, tcfg)
    gcfg = GenerateConfig(max_new_tokens=1000, top_k=50)
    prompt = jnp.zeros((batch_size, 1), jnp.int32)
    log(f"generate bench: B={batch_size}, 1000 tokens, top-k 50, "
        f"{mcfg.n_layer}L/{mcfg.n_head}H/{mcfg.n_embd}C")
    jax.device_get(generate(state.params, prompt, mcfg, gcfg))  # warm/compile
    timer = StepTimer()
    timer.start()
    for i in range(steps):
        toks = generate(state.params, prompt, mcfg, gcfg,
                        rng=jax.random.PRNGKey(i))
        timer.lap(toks)
    s = timer.summary(tokens_per_step=gcfg.max_new_tokens * batch_size)
    log(f"generate: p50 {s['p50_s'] * 1e3:.1f} ms/1k-tok, "
        f"{s['tokens_per_sec_per_chip']:,.0f} aggregate tok/s p50")
    # Distinct keys: B=1 is per-stream latency-derived throughput; B>1 is
    # aggregate (B x per-stream) — the same key would make artifacts from
    # the two modes silently incomparable.
    tps_key = ("generate_tokens_per_sec_p50" if batch_size == 1
               else "generate_aggregate_tokens_per_sec_p50")
    return {"generate_1k_p50_s": round(s["p50_s"], 4),
            tps_key: round(s["tokens_per_sec_per_chip"], 1),
            "batch_size": batch_size}


# HBM bandwidth by device_kind pattern, bytes/sec — for the decode
# roofline columns (benchmarks/RESULTS.md decode table convention).
# ORDERED, most-specific pattern first: matching walks the list, so a
# generic pattern added later can never shadow a specific one (the old
# dict relied on insertion order, and a substring like "v5" would have
# silently captured "v5p"/"v5 lite" depending on where it was added).
_HBM_BW = [
    ("v5 lite", 819e9), ("v5e", 819e9), ("v5p", 2765e9),
    ("v6", 1640e9), ("v4", 1228e9),
]

_HBM_BW_WARNED = set()


def hbm_bw_bytes_per_sec(device_kind: str) -> float | None:
    """First matching (pattern, bw) entry; logs once per unmatched kind
    so sweep rows missing the roofline columns are never silent."""
    kind = (device_kind or "").lower()
    for pat, bw in _HBM_BW:
        if pat in kind:
            return bw
    if kind not in _HBM_BW_WARNED:
        _HBM_BW_WARNED.add(kind)
        log(f"note: no HBM bandwidth entry for device kind "
            f"{device_kind!r}; roofline floor columns omitted")
    return None


def _decode_byte_floor_us(mcfg, batch: int, device_kind: str,
                          n_params: int):
    """Ideal µs/token for the 1k-token decode workload: every model
    parameter (bf16, the per-segment cast copies XLA hoists out of the
    token scan) plus the LOGICAL valid-prefix KV bytes per step, over
    the device's HBM bandwidth. Logical bytes on purpose: the ratio
    then exposes layout padding (the heads layout's D-minor tile pad)
    as excess, matching the RESULTS.md roofline convention. None when
    the device's bandwidth is unknown (e.g. CPU)."""
    bw = hbm_bw_bytes_per_sec(device_kind)
    if bw is None:
        return None
    weight_bytes = n_params * 2
    # avg valid-prefix cache read per step over 1k tokens (window refresh
    # caps pos at block_size; itemsize 2 = bf16 cache)
    S = mcfg.block_size
    avg_pos = sum(min(t, S) for t in range(1, 1001)) / 1000
    kv_bytes = 2 * mcfg.n_layer * batch * avg_pos * mcfg.n_embd * 2
    return (weight_bytes + kv_bytes) / bw * 1e6


def bench_decode_sweep(args) -> None:
    """Batched decode: aggregate tok/s vs batch size, one model/state
    reused across the sweep (the RESULTS.md batched-decode table).
    ``--decode-cache-layout`` overrides the KV-cache layout for the
    hardware heads/packed A/B (tools/hw_validate.py
    decode_sweep_packed)."""
    import dataclasses

    import jax

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config(args.preset)
    if args.decode_cache_layout:
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model, decode_cache_layout=args.decode_cache_layout))
        log(f"decode cache layout: {args.decode_cache_layout}")
    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)
    rows = {}
    laps = min(args.steps, 8)  # per-lap cost grows with B; 5-8 laps
    dev = jax.devices()[0]
    from replicatinggpt_tpu.models.gpt import param_count
    n_params = param_count(state.params)
    for B in (int(b) for b in args.decode_batch_sizes.split(",")):
        r = measure_generate_p50(cfg.model, cfg.train, steps=laps,
                                 batch_size=B, state=state)
        floor = _decode_byte_floor_us(cfg.model, B, dev.device_kind,
                                      n_params)
        if floor is not None:
            r["byte_floor_us_per_tok"] = round(floor, 1)
            r["x_floor"] = round(
                r["generate_1k_p50_s"] * 1e6 / 1000 / floor, 2)
        rows[f"B{B}"] = r
    last = rows[sorted(rows, key=lambda k: int(k[1:]))[-1]]
    emit({
        "metric": "generate_batched_aggregate_tokens_per_sec_p50",
        "value": last.get("generate_aggregate_tokens_per_sec_p50",
                          last.get("generate_tokens_per_sec_p50")),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,  # reference publishes no generation numbers
        "sweep": rows,
    })


def bench_serve(args) -> None:
    """Continuous-batching serving replay (serve/): a seeded Poisson
    trace through the pooled-KV engine; artifact is the aggregate
    decode throughput plus the TTFT/step-latency/occupancy summary and
    the recompiles-after-warmup count (must be 0 at steady state).

    ``--spec`` switches on speculative decoding over a repetitive
    greedy trace (the drafter's favorable regime — the point of the
    artifact is the serving-side multiplier: accept rate and mean
    committed tokens per slot-step, which is 1.0 exactly without
    speculation). ``--draft-model <preset>`` swaps the host-side
    n-gram drafter for a small random-init draft model.

    ``--serve-prefix-trace`` replays the system-prompt traffic shape
    instead (every prompt shares one common prefix) TWICE — radix
    prefix cache on, then off on the same trace — so the artifact's
    TTFT delta is the prefix cache's, not the workload's. Every serve
    artifact carries the paged-pool block (pages_in_use /
    page_utilization / prefix_hit_rate / evictions / cow_copies)."""
    import jax

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.serve import EngineConfig, ReplayConfig, run_replay
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config(args.preset)
    dev = jax.devices()[0]
    spec_mode = ("model" if args.spec and args.draft_model
                 else "ngram" if args.spec else "off")
    prompt_mode = ("shared_prefix" if args.serve_prefix_trace
                   else "repeat" if args.spec else "random")
    log(f"serve replay: {args.serve_requests} requests @ "
        f"{args.serve_rate}/s, pool {args.serve_pool}, spec {spec_mode}, "
        f"trace {prompt_mode}, model {cfg.model.n_layer}L/"
        f"{cfg.model.n_head}H/{cfg.model.n_embd}C on {dev.device_kind}")
    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)
    rcfg = ReplayConfig(n_requests=args.serve_requests,
                        rate=args.serve_rate, seed=0,
                        prompt_len_max=cfg.model.block_size // 2,
                        max_new_tokens=args.serve_max_new_tokens,
                        top_k=50,
                        # the speculative artifact measures the
                        # multiplier where drafting can win: repetitive
                        # prompts, greedy (deterministic accept rule)
                        greedy=bool(args.spec),
                        prompt_mode=prompt_mode,
                        spec=spec_mode, spec_k=args.spec_k)
    draft_params = draft_cfg = None
    if spec_mode == "model":
        from replicatinggpt_tpu.models.gpt import init_params
        from replicatinggpt_tpu.serve import draft_config_from_preset
        draft_cfg = draft_config_from_preset(cfg.model, args.draft_model)
        draft_params = init_params(jax.random.PRNGKey(1), draft_cfg)
        log(f"draft model: {args.draft_model} -> {draft_cfg.n_layer}L/"
            f"{draft_cfg.n_head}H/{draft_cfg.n_embd}C (random init)")
    # detection-only resilience defaults: stall watchdog + speculative
    # auto-disable on (healthy runs pay only the bookkeeping — the
    # robustness overhead this artifact's trajectory tracks), shedding
    # off (it would change the measured workload)
    from replicatinggpt_tpu.faults import DEFAULT_SERVE_RESILIENCE
    from replicatinggpt_tpu.parallel.mesh import resolve_mesh_shape
    mesh_d, mesh_m = resolve_mesh_shape(args.mesh_shape,
                                        len(jax.devices()), warn=log)
    if mesh_d * mesh_m > 1:
        log(f"serving mesh: {mesh_d}x{mesh_m} (data x model)")
    if args.kv_quant != "none" or args.weight_quant != "none":
        log(f"quantization: kv {args.kv_quant}, weights "
            f"{args.weight_quant}")
    ecfg = EngineConfig(pool_size=args.serve_pool,
                        max_queue=2 * args.serve_requests,
                        page_size=args.serve_page_size,
                        n_pages=args.serve_n_pages,
                        decode_window=args.decode_window,
                        decode_window_auto=args.decode_window_auto,
                        mesh_data=mesh_d, mesh_model=mesh_m,
                        kv_quant=args.kv_quant,
                        weight_quant=args.weight_quant,
                        act_quant=args.act_quant,
                        paged_kernel=args.paged_kernel)
    summary = run_replay(state.params, cfg.model, rcfg, ecfg,
                         draft_params=draft_params, draft_cfg=draft_cfg,
                         resilience=DEFAULT_SERVE_RESILIENCE,
                         trace_out=args.trace_out,
                         metrics_timeline=args.metrics_timeline,
                         metrics_out=args.metrics_out)
    if "artifacts" in summary:
        log(f"observability artifacts: {summary['artifacts']}")
    h = summary["histograms"]
    sp = summary.get("speculative") or {}
    pg = summary["pages"]
    dp = summary.get("dispatch", {})
    dispatch_split: dict = {}
    # spec mode keeps the verify program as the steady-state dispatch
    # (windows only engage while speculation is degraded), so the
    # blocked-vs-amortized A/B is only meaningful without a drafter
    if args.decode_window > 1 and spec_mode == "off":
        # the serve-side dispatch split the train bench has had since
        # BENCH_r03 (77.4 ms blocked vs 12.1 ms/step amortized at k=25):
        # replay the SAME request set at BOTH window sizes and compare
        # host-overhead per decoded token. Both arms run at a
        # saturating arrival rate — the split measures steady-state
        # dispatch amortization. CPU caveat (continuous windows): with
        # the launch-input caching both arms now skip the per-dispatch
        # device_puts that used to dominate this number, and what
        # remains of a CPU "launch" is XLA:CPU executing thunks inline
        # on the dispatching thread — device time proportional to k —
        # so on CPU this ratio can sit near/below 1.0 while the
        # deterministic dispatch-count split (admission_storm block)
        # shows the real amortization; the TPU row carries the
        # wall-clock multiplier
        import dataclasses
        dense = dataclasses.replace(rcfg,
                                    rate=max(rcfg.rate, 10_000.0))
        windowed = run_replay(state.params, cfg.model, dense, ecfg,
                              resilience=DEFAULT_SERVE_RESILIENCE)
        blocked = run_replay(state.params, cfg.model, dense,
                             dataclasses.replace(ecfg, decode_window=1),
                             resilience=DEFAULT_SERVE_RESILIENCE)
        wdp = windowed.get("dispatch", {})
        bdp = blocked.get("dispatch", {})
        amortized = wdp.get("host_dispatch_ms_per_token", 0.0)
        per_tok_blocked = bdp.get("host_dispatch_ms_per_token", 0.0)
        # the headline replay's numbers stay the top-level
        # decode_window_k / decode_dispatch_ms /
        # host_dispatch_ms_per_token keys; this block is the dense A/B
        dispatch_split = {
            "host_ms_per_token": amortized,
            "host_ms_per_token_blocked": per_tok_blocked,
            "host_overhead_speedup": (
                round(per_tok_blocked / amortized, 3)
                if amortized > 0 else 0.0),
            "recompiles_after_warmup_blocked":
                blocked["recompiles_after_warmup"],
        }
        log(f"dispatch split (saturating-rate A/B): host "
            f"{per_tok_blocked:.3f} ms/token blocked (k=1) vs "
            f"{amortized:.3f} ms/token amortized "
            f"(k={args.decode_window}) -> "
            f"{dispatch_split['host_overhead_speedup']}x")
    storm_block: dict = {}
    if args.serve_storm_trace and args.decode_window > 1 \
            and spec_mode == "off":
        # the continuous-window acceptance workload (ISSUE 13): an
        # admission-heavy saturating trace with mixed deadlines and
        # mid-flight cancels, replayed at window k and blocked k=1.
        # Amortization is the DETERMINISTIC dispatch-count split
        # (dispatches per decoded token, blocked over windowed);
        # retention compares it against the same trace with the
        # lifecycle churn stripped — the pre-continuous-windows
        # engine collapses to ~1.0 under the storm by construction.
        from replicatinggpt_tpu.serve.loadgen import (
            AdmissionStormConfig, admission_storm)
        scfg = AdmissionStormConfig(n_requests=args.serve_requests)
        strace, scancels, sdeadlines = admission_storm(cfg.model, scfg)

        def amortization(cancels, deadlines):
            import dataclasses as _dc
            out = {}
            for label, e in (("windowed", ecfg),
                             ("blocked",
                              _dc.replace(ecfg, decode_window=1))):
                s = run_replay(state.params, cfg.model, rcfg, e,
                               resilience=DEFAULT_SERVE_RESILIENCE,
                               trace=[(t, _dc.replace(r))
                                      for t, r in strace],
                               cancels=cancels, deadlines=deadlines)
                c = s["counters"]
                out[label] = (s, c["decode_dispatches"]
                              / max(c["decode_tokens"], 1))
            return out["windowed"], out["blocked"]

        (storm_w, dpt_sw), (_, dpt_sb) = amortization(scancels,
                                                      sdeadlines)
        (idle_w, dpt_iw), (_, dpt_ib) = amortization([], {})
        a_storm = dpt_sb / dpt_sw
        a_idle = dpt_ib / dpt_iw
        storm_block = {
            "n_requests": scfg.n_requests,
            "deadline_frac": scfg.deadline_frac,
            "cancel_frac": scfg.cancel_frac,
            "amortization_storm": round(a_storm, 3),
            "amortization_idle": round(a_idle, 3),
            "retention": (round(a_storm / a_idle, 4) if a_idle else 0.0),
            "window_breaks": storm_w["window_breaks"],
            "recompiles_after_warmup":
                storm_w["recompiles_after_warmup"],
        }
        log(f"admission storm: {a_storm:.2f}x dispatch amortization "
            f"under the storm vs {a_idle:.2f}x idle -> "
            f"{storm_block['retention']:.1%} retained "
            f"(breaks {storm_w['window_breaks']})")
    quant_ab: dict = {}
    if args.quant_ab:
        # bf16-vs-int8 KV at FIXED HBM on the shared-prefix trace
        # (ISSUE 15 acceptance): one byte budget, each arm sized in ITS
        # pages (pages.n_pages_for_hbm) — page count is the admission
        # currency, so the int8 arm admits ~2x the concurrent requests
        # the budget allows the baseline. The budget is deliberately
        # HALF the default pool so pages (not slots) are the binding
        # constraint and the capacity win shows up as queue wait, not
        # just a bigger idle pool. Divergence rides the same block:
        # both arms replay an identical greedy trace through fresh
        # engines and the streams are compared token-for-token.
        import dataclasses
        from replicatinggpt_tpu.serve import Engine
        from replicatinggpt_tpu.serve.pages import (n_pages_for_hbm,
                                                    page_bytes,
                                                    pool_geometry)
        from replicatinggpt_tpu.serve.replay import make_trace
        psz, mp, n_default = pool_geometry(
            cfg.model, args.serve_pool, args.serve_page_size, 0,
            args.serve_n_pages)
        pb_base = page_bytes(cfg.model, psz)
        pb_int8 = page_bytes(cfg.model, psz, "int8")
        hbm = pb_base * max(n_default // 2, mp)
        ab_rcfg = dataclasses.replace(
            rcfg, prompt_mode="shared_prefix", greedy=True, spec="off",
            rate=max(rcfg.rate, 10_000.0))
        arms = {}
        streams = {}
        for label, kvq in (("base", "none"), ("int8", "int8")):
            n_p = max(n_pages_for_hbm(hbm, cfg.model, psz, kvq), mp)
            e = dataclasses.replace(ecfg, kv_quant=kvq, n_pages=n_p,
                                    weight_quant="none")
            arms[label] = (run_replay(state.params, cfg.model, ab_rcfg,
                                      e,
                                      resilience=DEFAULT_SERVE_RESILIENCE),
                           n_p)
            # divergence arm: the SAME greedy request set through a
            # fresh engine, streams compared token-for-token
            eng = Engine(state.params, cfg.model,
                         dataclasses.replace(e, max_queue=4096))
            div_trace = make_trace(cfg.model, dataclasses.replace(
                ab_rcfg, n_requests=min(16, args.serve_requests)))
            for _, r in div_trace:
                eng.submit(dataclasses.replace(r, deadline=None))
            streams[label] = {r.id: list(r.tokens)
                              for r in eng.drain()}
        matches = [streams["base"][rid] == streams["int8"][rid]
                   for rid in streams["base"]]
        sb, n_b = arms["base"]
        si, n_i = arms["int8"]

        def _pick(s):
            h2 = s["histograms"]
            return {
                "queue_wait_p50_ms": round(
                    h2.get("queue_wait_s", {}).get("p50", 0) * 1e3, 2),
                "ttft_p50_ms": round(
                    h2.get("ttft_s", {}).get("p50", 0) * 1e3, 2),
                "prefix_hit_rate": s["pages"]["prefix_hit_rate"],
                "recompiles_after_warmup": s["recompiles_after_warmup"],
            }

        quant_ab = {
            "kv_dtype": "int8",
            "hbm_budget_bytes": hbm,
            "bytes_per_page": {"base": pb_base, "int8": pb_int8},
            "n_pages": {"base": n_b, "int8": n_i},
            "capacity_ratio": round(n_i / n_b, 3),
            "greedy_stream_match_rate": round(
                sum(matches) / len(matches), 3),
            "base": _pick(sb),
            "int8": _pick(si),
        }
        log(f"quant A/B (fixed {hbm / 1e6:.2f} MB KV budget): "
            f"{n_b} pages base vs {n_i} pages int8 "
            f"({quant_ab['capacity_ratio']}x capacity), greedy stream "
            f"match {quant_ab['greedy_stream_match_rate']:.0%}, queue "
            f"wait p50 {quant_ab['base']['queue_wait_p50_ms']} -> "
            f"{quant_ab['int8']['queue_wait_p50_ms']} ms")
    prefix_ab: dict = {}
    if args.serve_prefix_trace:
        # same trace, radix prefix cache OFF: the TTFT delta isolates
        # the prefix cache (prompt lengths, arrivals, sampling all fixed)
        import dataclasses
        off = run_replay(state.params, cfg.model, rcfg,
                         dataclasses.replace(ecfg, prefix_cache=False),
                         draft_params=draft_params, draft_cfg=draft_cfg,
                         resilience=DEFAULT_SERVE_RESILIENCE)
        ttft_on = h.get("ttft_s", {}).get("mean", 0) * 1e3
        ttft_off = (off["histograms"].get("ttft_s", {}).get("mean", 0)
                    * 1e3)
        prefix_ab = {
            "ttft_mean_ms": round(ttft_on, 3),
            "ttft_mean_ms_no_prefix_cache": round(ttft_off, 3),
            "ttft_mean_speedup": (round(ttft_off / ttft_on, 3)
                                  if ttft_on > 0 else 0.0),
            "prefill_tokens": summary["counters"].get("prefill_tokens", 0),
            "prefill_tokens_no_prefix_cache":
                off["counters"].get("prefill_tokens", 0),
        }
        log(f"prefix A/B: TTFT mean {ttft_on:.2f} ms cached vs "
            f"{ttft_off:.2f} ms uncached "
            f"({pg['prefix_hit_tokens']} prefix-hit tokens)")
    log(f"serve: {summary['aggregate_tokens_per_s']} tok/s aggregate, "
        f"TTFT p50 {h.get('ttft_s', {}).get('p50', 0) * 1e3:.1f} ms, "
        f"{summary['recompiles_after_warmup']} recompiles after warmup, "
        f"pages {pg['pages_in_use']}/{pg['n_pages']}, prefix hit rate "
        f"{pg['prefix_hit_rate']}"
        + (f", accept rate {sp['accept_rate']}, "
           f"{sp['mean_tokens_per_step']} tok/slot-step" if sp else ""))
    emit({
        "metric": "serve_replay_aggregate_tokens_per_sec",
        "value": summary["aggregate_tokens_per_s"],
        "unit": "tokens/sec",
        "vs_baseline": 0.0,  # reference has no serving path at all
        "n_requests": summary["n_requests"],
        "n_completed": summary["n_completed"],
        "ttft_p50_ms": round(h.get("ttft_s", {}).get("p50", 0) * 1e3, 2),
        "ttft_p99_ms": round(h.get("ttft_s", {}).get("p99", 0) * 1e3, 2),
        "step_p50_ms": round(summary["step_latency"]["p50_s"] * 1e3, 3),
        "batch_fill_mean": round(
            h.get("batch_fill_ratio", {}).get("mean", 0), 3),
        "recompiles_after_warmup": summary["recompiles_after_warmup"],
        # async-engine dispatch amortization (the BENCH_r03 gap's serve
        # proxy): mean host ms per decode dispatch + the chosen window
        "decode_window_k": dp.get("window_k", 1),
        "decode_dispatch_ms": dp.get("mean_dispatch_ms", 0.0),
        "host_dispatch_ms_per_token": dp.get("host_dispatch_ms_per_token",
                                             0.0),
        "device_kind": dev.device_kind,
        # paged KV pool health (serve/pages.py) — the dashboard keys the
        # acceptance criteria name explicitly
        "pages_in_use": pg["pages_in_use"],
        "page_utilization": pg["page_utilization"],
        "page_size": pg["page_size"],
        # serving mesh (ISSUE 12): the EFFECTIVE shape (1x1 when the
        # backend had too few devices), per-chip page capacity, and the
        # aggregate admission currency — n_pages is aggregate, each
        # data-axis chip physically stores pages_per_chip of it
        "mesh_shape": pg["mesh_shape"],
        "pages_per_chip": pg["pages_per_chip"],
        "aggregate_pages": pg["aggregate_pages"],
        "prefix_hit_rate": pg["prefix_hit_rate"],
        "prefix_hit_tokens": pg["prefix_hit_tokens"],
        "evictions": pg["evictions"],
        "cow_copies": pg["cow_copies"],
        # self-healing counters (faults/): nonzero means the measured
        # run was degraded — the number is then not a healthy-path claim
        "recovery": {k: summary["recovery"][k]
                     for k in ("watchdog_stalls", "spec_disables",
                               "spec_reprobes", "shed_requests")},
        # continuous-window health: which host mutations still broke
        # windows in the headline replay (admit/deadline/cancel should
        # be zero — only spec reasons may move), and the autotuned k
        "window_breaks": summary.get("window_breaks", {}),
        # quantization (ISSUE 15): the pool's storage mode + the
        # capacity denominator ride every serve artifact
        "kv_quant": pg["kv_quant"],
        "bytes_per_page": pg["bytes_per_page"],
        # kernel-route decision (ISSUE 20): which step families ran the
        # unified Pallas kernel family vs XLA, with the envelope
        # reasons for any fallback — schema pinned in tests/test_pages
        "kernel_route": summary.get("kernel_route", {}),
        **({"speculative": sp} if sp else {}),
        **({"dispatch_split": dispatch_split} if dispatch_split else {}),
        **({"admission_storm": storm_block} if storm_block else {}),
        **({"prefix_ab": prefix_ab} if prefix_ab else {}),
        **({"quant_ab": quant_ab} if quant_ab else {}),
        # observability artifacts (utils.telemetry): paths + counts of
        # the Perfetto trace / metrics timeline / Prometheus text this
        # run emitted, so the dashboard can link the evidence
        **({"artifacts": summary["artifacts"]}
           if "artifacts" in summary else {}),
    })


def _ttft_ms(results, lcfg, want_long, session_is_long, q=0.99):
    """Percentile TTFT (ms) over the long or short slice of a fleet
    replay's per-request results (request ids are ``s{sid:03d}t{k}``)."""
    import numpy as np
    vals = [r.ttft_s for r in results.values()
            if r.ok and session_is_long(int(r.id[1:4]), lcfg) == want_long]
    if not vals:
        return 0.0
    return round(float(np.quantile(np.asarray(vals), q)) * 1e3, 2)


def bench_fleet_disagg_ab(args, cfg, lcfg, ecfg, dev) -> None:
    """The disaggregation A/B (``--mode fleet --disagg``): the SAME
    mixed long+short session trace through two fleets of equal worker
    count — colocated (every replica prefills and decodes) vs
    disaggregated (one prefill worker feeds N-1 decode workers over
    ``page_transfer``). The claim under test: long prompts monopolize
    colocated batch budget and spike short-prompt TTFT; pulling them
    onto a prefill tier keeps the decode tier's windows dense, so
    short-prompt TTFT p99 drops at identical capacity. The artifact's
    ``disagg_ab`` block carries both arms' short/long TTFT, the
    transfer-path counters + latency, and the token-identity bit
    (greedy streams must match across arms — placement must never
    change results).

    On CPU both arms replay on the fleet's deterministic VIRTUAL step
    clock (loadgen.StepClock, ``virtual_dt``): this box serializes all
    replicas through one device (and CI containers are single-core),
    so wall-clock TTFT here measures compute serialization identically
    in both arms — not placement. Virtual TTFT counts router
    scheduling steps — FIFO slot wait, chunked-prefill progress,
    per-chunk transfer round-trips — which is precisely the structure
    disaggregation changes, and is reproducible bit-for-bit run to
    run. The real wall-clock row runs on TPU hardware
    (tools/hw_drain.sh; benchmarks/RESULTS.md has it queued)."""
    import dataclasses

    import jax

    from replicatinggpt_tpu.serve import RouterConfig, run_fleet_replay
    from replicatinggpt_tpu.serve.loadgen import session_is_long
    from replicatinggpt_tpu.train.state import create_train_state

    block = cfg.model.block_size
    n = args.fleet_replicas
    if n < 2:
        raise SystemExit("--disagg needs --fleet-replicas >= 2 "
                         "(one prefill tier + at least one decode)")
    # TTFT is a PROMPT-phase metric, so the A/B trace is prefill-heavy
    # by construction: short decode budgets (slots turn over on prompt
    # work, not decode), every 2nd session opening a unique
    # near-block-size prompt — the largest prefill the trace can carry
    max_new = min(lcfg.max_new_tokens, 4)
    user_len = min(lcfg.user_len_max, 4)
    long_len = max(block - lcfg.turns * (user_len + max_new),
                   lcfg.prefix_len + 1)
    lcfg = dataclasses.replace(lcfg, max_new_tokens=max_new,
                               user_len_max=user_len,
                               long_every=2, long_prefix_len=long_len)
    # the two policy knobs that make the A/B measure what it claims:
    # (1) only LONG prompts divert to the prefill tier — the tail
    # threshold sits at half the long prompt, far above any short
    # session's uncached pages; (2) both arms run a deliberately small
    # pool, because the phenomenon under test IS saturation (an
    # unsaturated colocated fleet admits every short instantly and
    # there is nothing for disaggregation to win back)
    min_tail = max(2, (long_len // ecfg.page_size) // 2)
    # a small prefill chunk restores the accelerator's compute ratio on
    # CPU: a real TPU's long-prompt prefill costs ~50x a decode step,
    # but this CPU model's 64-token chunk costs about ONE decode step —
    # chunking at 16 makes a near-block-size prompt many dispatches
    # while shorts stay at 2-3, which is the asymmetry the prefill
    # tier exists to absorb (both arms run the identical config)
    # pool headroom on the PAGE axis only: an in-flight transfer pins
    # the request's full prompt on the decode worker before it owns a
    # slot, so the decode pool needs pages beyond pool_size * max_pages
    # or transfers lose the pool race to admission (sink_refused)
    # the windowed engine (decode_window > 1) paces prefill one chunk
    # per window iteration — prompt length costs router STEPS in
    # proportion, which the k=1 path hides (it prefills a whole prompt
    # inside one step); pool_size=1 makes FIFO slot wait visible
    # the page pool is sized EVICTION-FREE (worst-case every session
    # resident on one replica, plus transfer-pin headroom): the two
    # arms evict in different orders, and under KV quantization an
    # evicted prefix does not recompute bit-identically (the original
    # decode-path rows attended dequantized cache; the recomputed
    # prefill rows attend fresh in-chunk values) — token identity
    # across placements is only a meaningful invariant when neither
    # arm evicts, and slot scarcity (pool_size=1), not page scarcity,
    # is the saturation under test
    max_pages = -(-block // ecfg.page_size)
    pool = 1
    ecfg = dataclasses.replace(ecfg, pool_size=pool, prefill_chunk=16,
                               n_pages=(lcfg.n_sessions + pool + 2)
                               * max_pages,
                               decode_window=2,
                               kv_quant=args.kv_quant)
    # saturating arrivals: every session is queued almost immediately
    # (in virtual time), so TTFT measures queueing structure, not
    # arrival spacing
    lcfg = dataclasses.replace(lcfg, rate=2000.0)
    dt = 0.01                       # one router step = 10 virtual ms

    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)

    def arm(tiers, tag):
        rcfg = RouterConfig(n_replicas=n, tiers=tiers,
                            disagg_min_tail=min_tail)
        t0 = time.time()
        s = run_fleet_replay(state.params, cfg.model, lcfg, rcfg, ecfg,
                             virtual_dt=dt, collect_streams=True)
        log(f"{tag}: {s['n_completed']}/{s['n_requests']} turns in "
            f"{time.time() - t0:.1f}s wall, short TTFT p99 "
            f"{_ttft_ms(s['results'], lcfg, False, session_is_long)} "
            f"virtual ms")
        return s

    log(f"disagg A/B: {lcfg.n_sessions} sessions (every 2nd opens "
        f"{long_len}-tok unique prompt), {n} workers each arm")
    colo = arm(None, "colocated")
    dis = arm(("prefill",) + ("decode",) * (n - 1), "disagg")
    identical = colo["streams"] == dis["streams"]

    def side(s):
        return {
            "short_ttft_p50_ms": _ttft_ms(s["results"], lcfg, False,
                                          session_is_long, 0.50),
            "short_ttft_p99_ms": _ttft_ms(s["results"], lcfg, False,
                                          session_is_long),
            "long_ttft_p99_ms": _ttft_ms(s["results"], lcfg, True,
                                         session_is_long),
            "n_completed": s["n_completed"],
            "wall_s": s["wall_s"],
            "recompiles_after_warmup": s["recompiles_after_warmup"],
        }

    rc = dis["router"]
    colo_p99 = _ttft_ms(colo["results"], lcfg, False, session_is_long)
    dis_p99 = _ttft_ms(dis["results"], lcfg, False, session_is_long)
    log(f"disagg A/B: short TTFT p99 {colo_p99} ms colocated -> "
        f"{dis_p99} ms disagg, tokens_identical={identical}, "
        f"{rc.get('fleet_transfers', 0)} transfers "
        f"({rc.get('fleet_transfer_bytes', 0)} B)")
    emit({
        "metric": "fleet_disagg_short_ttft_p99_ms",
        "value": dis_p99,
        "unit": "virtual_ms",
        "vs_baseline": colo_p99,
        "device_kind": dev.device_kind,
        "disagg_ab": {
            "clock": f"virtual-step (dt={dt * 1e3:g} ms/router-step)",
            "workers_per_arm": n,
            "kv_quant": ecfg.kv_quant,
            "tiers": {"prefill": 1, "decode": n - 1},
            "trace": {"n_sessions": lcfg.n_sessions,
                      "turns": lcfg.turns,
                      "long_every": lcfg.long_every,
                      "long_prefix_len": long_len},
            "colocated": side(colo),
            "disagg": {
                **side(dis),
                "disagg_prefills": rc.get("fleet_disagg_prefills", 0),
                "shortcircuits":
                    rc.get("fleet_disagg_shortcircuits", 0),
                "fallbacks": rc.get("fleet_disagg_fallbacks", 0),
                "transfers": rc.get("fleet_transfers", 0),
                "transfer_pages": rc.get("fleet_transfer_pages", 0),
                "transfer_bytes": rc.get("fleet_transfer_bytes", 0),
                "transfer_failures":
                    rc.get("fleet_transfer_failures", 0),
                "transfer_p99_ms": round(
                    dis["transfer_s"].get("p99", 0) * 1e3, 3),
            },
            "tokens_identical": identical,
            "short_ttft_p99_improves": dis_p99 < colo_p99,
        },
    })


def bench_fleet(args) -> None:
    """Fleet serving replay (serve/router.py + serve/loadgen.py):
    multi-turn session traffic through N engine replicas behind the
    prefix-affinity router, in wall-clock time. The artifact is the
    fleet's aggregate decode throughput plus the blocks the fleet
    acceptance criteria key on: per-replica occupancy and pages,
    requeue/re-route counters, the fleet TTFT distribution, and the
    aggregate prefix-hit rate (affinity keeps it near a single
    replica's on the same workload).

    ``--fleet-kill-at N`` injects a deterministic ``replica_kill`` of
    replica 0 at router step N mid-run (faults/fleet.py): the artifact
    then also demonstrates the requeue path — every in-flight request
    finishes via the crash journal, and the run is tagged
    ``chaos: replica_kill``.

    ``--multiproc`` runs the replicas as real worker PROCESSES
    (serve-worker + faults/procsup.py supervisor) registering over
    RPC, each with a PRIVATE journal dir: the artifact gains
    per-worker pid/restart counts and the requeue-latency
    distribution, and ``--fleet-kill-at`` becomes a REAL ``SIGKILL``
    of worker 0's process (``proc_kill``) — recovery is supervised
    restart + journal replay, and the completed turn count still has
    to come out whole. ``--fleet-host-loss`` upgrades the kill to
    ``host_loss`` (SIGKILL + the worker's journal/workdir deleted):
    recovery is then the ROUTER's own request ledger, nothing on the
    worker's filesystem survives by construction.

    ``--fleet-load-step`` is the autoscaler preset: ONE worker starts,
    session arrivals double mid-run then halve
    (``SessionLoadConfig.load_step``), and the supervisor's autoscaler
    spawns/drains workers from the router's offered-load gauges up to
    ``--fleet-replicas``. The artifact emits scale-up/scale-down
    counts, peak/final worker counts, and the zero-drop verification
    (completed == submitted)."""
    import jax

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.faults import Fault, FaultPlan, installed
    from replicatinggpt_tpu.faults.fleet import (FLEET_STEP,
                                                 KIND_HOST_LOSS,
                                                 KIND_PROC_KILL,
                                                 KIND_REPLICA_KILL)
    from replicatinggpt_tpu.serve import (EngineConfig, RouterConfig,
                                          SessionLoadConfig,
                                          run_fleet_replay)
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config(args.preset)
    dev = jax.devices()[0]
    block = cfg.model.block_size
    # size turns to the model's context: prefix + turns*(user+gen) must
    # fit block_size with headroom
    prefix_len = min(args.fleet_prefix_len, block // 4)
    max_new = min(args.serve_max_new_tokens,
                  max((block - prefix_len) // (2 * args.fleet_turns), 1))
    user_len = max(min(max_new // 2, 8), 1)
    multiproc = args.multiproc or args.fleet_load_step
    if args.fleet_host_loss and not multiproc:
        raise SystemExit("--fleet-host-loss requires --multiproc "
                         "(host loss is a real SIGKILL + workdir "
                         "deletion of a worker PROCESS; the "
                         "in-process fleet has no host to lose)")
    if getattr(args, "net_chaos", False) and not multiproc:
        raise SystemExit("--net-chaos requires --multiproc (netchaos "
                         "faults land on the fleet RPC wire; the "
                         "in-process fleet has no wire to hurt)")
    lcfg = SessionLoadConfig(
        n_sessions=args.fleet_sessions, turns=args.fleet_turns,
        n_prefix_groups=args.fleet_prefix_groups, prefix_len=prefix_len,
        user_len_min=1, user_len_max=user_len, max_new_tokens=max_new,
        rate=args.serve_rate, greedy=True, seed=0,
        load_step=args.fleet_load_step)
    rcfg = RouterConfig(n_replicas=args.fleet_replicas,
                        journal_dir=args.fleet_journal_dir or None)
    # default the page size so the shared prefix spans >= 2 full pages
    # (radix sharing works on whole pages; a prefix shorter than one
    # page would make the artifact's hit-rate block structurally zero)
    page_size = args.serve_page_size or max(2, min(16, prefix_len // 2))
    ecfg = EngineConfig(pool_size=args.serve_pool,
                        max_queue=4 * args.fleet_sessions,
                        page_size=page_size,
                        n_pages=args.serve_n_pages)
    if getattr(args, "disagg", False):
        bench_fleet_disagg_ab(args, cfg, lcfg, ecfg, dev)
        return
    n_initial = 1 if args.fleet_load_step else rcfg.n_replicas
    log(f"fleet replay: {lcfg.n_sessions} sessions x {lcfg.turns} turns "
        f"@ {lcfg.rate}/s{' (load-step x2 then /2)' if lcfg.load_step else ''} "
        f"over {n_initial} "
        f"{'worker process' if multiproc else 'replica'}(s)"
        f"{f' (autoscale <= {rcfg.n_replicas})' if args.fleet_load_step else ''} "
        f"(pool {ecfg.pool_size} each), prefix {prefix_len} tok x "
        f"{lcfg.n_prefix_groups} groups, model {cfg.model.n_layer}L/"
        f"{cfg.model.n_head}H/{cfg.model.n_embd}C on {dev.device_kind}")
    import contextlib
    import tempfile
    plan_ctx = contextlib.nullcontext()
    chaos_kind = None
    chaos_faults = []
    if args.fleet_kill_at >= 0:
        # in-process: simulated replica_kill; multiproc: a REAL SIGKILL
        # of worker 0's OS process through the supervisor —
        # --fleet-host-loss additionally deletes its journal/workdir
        if not multiproc:
            chaos_kind = KIND_REPLICA_KILL
        elif args.fleet_host_loss:
            chaos_kind = KIND_HOST_LOSS
        else:
            chaos_kind = KIND_PROC_KILL
        chaos_faults.append(Fault(
            site=FLEET_STEP, kind=chaos_kind, at=args.fleet_kill_at,
            arg=0))
    if getattr(args, "net_chaos", False):
        # the wire-fault ladder, fleet-wide spellings: duplicated and
        # reordered submit frames (answered from the workers' reply
        # caches — rpc_dup_suppressed must account for every one),
        # delayed and dropped step frames (the ack/redelivery protocol
        # absorbs the losses), and a 3-call one-way partition (the
        # maybe-executed case: requests execute, responses vanish)
        from replicatinggpt_tpu.faults.netchaos import (KIND_NET_DELAY,
                                                        KIND_NET_DROP,
                                                        KIND_NET_DUP,
                                                        KIND_NET_PARTITION,
                                                        KIND_NET_REORDER,
                                                        net_site)
        chaos_faults += [
            Fault(site=net_site("*", "*", "submit"), kind=KIND_NET_DUP,
                  at=1, times=2),
            Fault(site=net_site("*", "*", "submit"),
                  kind=KIND_NET_REORDER, at=4),
            Fault(site=net_site("*", "*", "step"), kind=KIND_NET_DELAY,
                  at=10, times=2, arg=0.01),
            Fault(site=net_site("*", "*", "step"), kind=KIND_NET_DROP,
                  at=25),
            Fault(site=net_site("*", "*", "step"),
                  kind=KIND_NET_PARTITION, at=40, times=3, arg2=1),
        ]
        chaos_kind = ("net_chaos" if chaos_kind is None
                      else f"{chaos_kind}+net_chaos")
    if chaos_faults:
        plan_ctx = installed(FaultPlan(*chaos_faults))
    workers = None
    scale = None
    with tempfile.TemporaryDirectory() as td:
        import dataclasses
        if rcfg.journal_dir is None:
            # requeue-after-kill needs journals; default them to a temp
            # dir so the chaos arm always has the recovery path
            rcfg = dataclasses.replace(rcfg, journal_dir=td)
        if multiproc:
            from replicatinggpt_tpu.faults.procsup import (
                AutoscaleConfig, SupervisorConfig, make_worker_specs,
                spawn_fleet, worker_spec_factory)
            # the router's own ledger: host_loss recovery reads no
            # worker filesystem
            rcfg = dataclasses.replace(
                rcfg, ledger_path=os.path.join(rcfg.journal_dir,
                                               "router_ledger.jsonl"))
            config_args = ["--preset", args.preset]
            engine_args = ["--pool-size", str(ecfg.pool_size),
                           "--max-queue", str(ecfg.max_queue),
                           "--page-size", str(ecfg.page_size),
                           "--n-pages", str(ecfg.n_pages)]
            specs = make_worker_specs(n_initial, rcfg.journal_dir,
                                      config_args, engine_args)
            autoscale = spec_factory = None
            if args.fleet_load_step:
                autoscale = AutoscaleConfig(
                    min_workers=1,
                    max_workers=max(rcfg.n_replicas, 2),
                    up_backlog_per_worker=1.0, up_patience=2,
                    down_active_per_worker=2.0, down_patience=12,
                    cooldown_ticks=8)
                spec_factory = worker_spec_factory(
                    rcfg.journal_dir, config_args, engine_args)
            log(f"spawning {n_initial} worker process(es) "
                f"(private dirs under {rcfg.journal_dir}; RPC "
                f"registration)")
            tel = None
            if args.trace_out:
                # the pre-built-router replay exports the ROUTER's own
                # recorder — it must exist before spawn_fleet wires it
                from replicatinggpt_tpu.utils.telemetry import Telemetry
                tel = Telemetry()
            router, sup = spawn_fleet(specs, rcfg,
                                      SupervisorConfig(backoff_s=0.2),
                                      telemetry=tel,
                                      autoscale=autoscale,
                                      spec_factory=spec_factory)
            try:
                with plan_ctx:
                    summary = run_fleet_replay(
                        None, cfg.model, lcfg,
                        router=router, supervisor=sup,
                        trace_out=args.trace_out,
                        metrics_timeline=args.metrics_timeline,
                        metrics_out=args.metrics_out)
                workers = [{
                    "worker": h.spec.idx, "pid": h.pid, "gen": h.gen,
                    "restarts": h.restarts,
                    "crash_restarts": h.crash_restarts,
                    "state": h.state,
                } for h in sup.handles]
                if args.fleet_load_step:
                    from replicatinggpt_tpu.faults.procsup import RUNNING
                    # let the post-trace lull land: the scale-DOWN
                    # decision needs its patience window of idle ticks
                    # after the last session finished
                    lull_deadline = time.time() + 30.0
                    while (sup.scale_downs == 0 and sup.scale_ups > 0
                           and time.time() < lull_deadline):
                        router.step()
                        sup.tick()
                        time.sleep(0.01)
                    scale = {
                        "scale_ups": sup.scale_ups,
                        "scale_downs": sup.scale_downs,
                        "workers_peak": sup.peak_workers,
                        "workers_final": sum(
                            h.state == RUNNING for h in sup.handles),
                        "zero_drop": (summary["n_completed"]
                                      == summary["n_requests"]),
                    }
            finally:
                sup.stop_all()
                router.close()
                if tel is not None:
                    tel.close()
        else:
            state = create_train_state(jax.random.PRNGKey(0),
                                       cfg.model, cfg.train)
            with plan_ctx:
                summary = run_fleet_replay(
                    state.params, cfg.model, lcfg, rcfg, ecfg,
                    trace_out=args.trace_out,
                    metrics_timeline=args.metrics_timeline,
                    metrics_out=args.metrics_out)
    ttft = summary["fleet_ttft_s"]
    requeue_lat = summary["requeue_latency_s"]
    agg = (summary["generated_tokens"] / summary["wall_s"]
           if summary["wall_s"] > 0 else 0.0)
    log(f"fleet: {summary['n_completed']}/{summary['n_requests']} "
        f"turns completed, {round(agg, 1)} tok/s aggregate, fleet TTFT "
        f"p50 {ttft.get('p50', 0) * 1e3:.1f} ms, prefix hit rate "
        f"{summary['aggregate_prefix_hit_rate']}, requeued "
        f"{summary['router'].get('fleet_requeued_requests', 0)}, "
        f"{summary['recompiles_after_warmup']} recompiles after warmup")
    if scale is not None:
        log(f"autoscale: {scale['scale_ups']} up / "
            f"{scale['scale_downs']} down, peak "
            f"{scale['workers_peak']} workers, final "
            f"{scale['workers_final']}, zero_drop={scale['zero_drop']}")
    emit({
        "metric": "fleet_replay_aggregate_tokens_per_sec",
        "value": round(agg, 1),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,      # reference has no serving path at all
        "n_replicas": summary["n_replicas"],
        "n_alive": summary["n_alive"],
        "n_sessions": summary["n_sessions"],
        "turns_per_session": summary["turns_per_session"],
        "n_requests": summary["n_requests"],
        "n_completed": summary["n_completed"],
        "fleet_ttft_p50_ms": round(ttft.get("p50", 0) * 1e3, 2),
        "fleet_ttft_p99_ms": round(ttft.get("p99", 0) * 1e3, 2),
        "requeue_latency_p50_ms": round(
            requeue_lat.get("p50", 0) * 1e3, 2),
        "requeue_latency_p99_ms": round(
            requeue_lat.get("p99", 0) * 1e3, 2),
        "aggregate_prefix_hit_rate":
            summary["aggregate_prefix_hit_rate"],
        "recompiles_after_warmup": summary["recompiles_after_warmup"],
        "device_kind": dev.device_kind,
        # the fleet acceptance blocks: per-replica occupancy + pages,
        # and the router's requeue/health counters
        "router": summary["router"],
        "replicas": [{
            "replica": r["health"]["replica"],
            "alive": r["health"]["alive"],
            "occupancy_mean": r["occupancy_mean"],
            "n_steps": r["n_steps"],
            "pages_in_use": r.get("pages", {}).get("pages_in_use", 0),
            "page_utilization": r.get("pages", {})
            .get("page_utilization", 0.0),
            "prefix_hit_rate": r.get("pages", {})
            .get("prefix_hit_rate", 0.0),
            "finished": r["finished"],
        } for r in summary["replicas"]],
        **({"multiproc": True, "workers": workers}
           if multiproc else {}),
        **({"chaos": chaos_kind, "kill_at": args.fleet_kill_at}
           if chaos_kind is not None else {}),
        **({"load_step": True, **scale} if scale is not None else {}),
        **({"artifacts": summary["artifacts"]}
           if "artifacts" in summary else {}),
    })


def bench_generate(args) -> None:
    import jax

    from replicatinggpt_tpu.config import get_config

    cfg = get_config(args.preset)
    jax.devices()
    gen = measure_generate_p50(cfg.model, cfg.train, steps=args.steps)
    emit({
        "metric": "generate_1k_tokens_per_sec_p50",
        "value": gen["generate_tokens_per_sec_p50"],
        "unit": "tokens/sec",
        "vs_baseline": 0.0,  # reference publishes no generation numbers
    })


def bench_longctx(args) -> None:
    """Long-context single-chip training: one end-to-end train step
    (embeddings, K/V-streaming flash attention with in-kernel dropout,
    remat, loss, AdamW) at --longctx-t tokens, batch 1. Proves the
    sequence-length story past the reference's block_size cap
    (GPT1.py:106, GPT-2.py:109) on real hardware, not just the kernel
    in isolation."""
    import jax
    import numpy as np

    from replicatinggpt_tpu.config import ModelConfig, TrainConfig
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.train.steps import make_train_step

    T = args.longctx_t
    mcfg = ModelConfig(vocab_size=256, block_size=T, n_layer=4, n_head=4,
                       n_embd=256, dropout=0.1, attn_dropout=0.1,
                       dtype="bfloat16", remat=True, attention_impl="auto")
    tcfg = TrainConfig(batch_size=1, lr=1e-3)
    dev = jax.devices()[0]
    log(f"longctx: T={T}, 4L/4H/256C bf16 remat, dropout 0.1, "
        f"{dev.device_kind}")
    state = create_train_state(jax.random.PRNGKey(0), mcfg, tcfg)
    step = make_train_step(mcfg, tcfg)
    toks = np.random.default_rng(0).integers(0, 256, (1, T + 1),
                                             dtype=np.int32)
    batch = (toks[:, :-1], toks[:, 1:])  # next-token targets, as training
    t0 = time.perf_counter()
    state, m = step(state, batch)
    loss = float(jax.device_get(m["loss"]))
    log(f"compile+first step {time.perf_counter() - t0:.0f}s, loss {loss:.3f}")
    assert np.isfinite(loss)
    t0 = time.perf_counter()
    n = 3
    for _ in range(n):
        state, m = step(state, batch)
    loss = float(jax.device_get(m["loss"]))  # blocks the timer; end-of-run
    dt = (time.perf_counter() - t0) / n
    emit({
        "metric": f"longctx_t{T}_train_tokens_per_sec_per_chip",
        "value": round(T / dt, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": 0.0,  # reference hard-caps T at 256/1024
        "step_ms": round(dt * 1e3, 1),
        "final_loss": round(loss, 4),
        "device_kind": dev.device_kind,
    })


def _repeat_median(fn, *, repeats: int, inner: int) -> dict:
    """Run ``fn`` (one timed lap = ``inner`` dispatched iterations ending
    in a real device fetch) ``repeats`` times and report median + spread.

    The tunnel's run-to-run noise on kernel microbenches reached 2x in
    round 2 (3.6-7.6 ms for the same kernel at BH=192/T=1024 —
    benchmarks/RESULTS.md), swamping remaining kernel deltas; medians
    over >= 5 repeats with the spread attached are the defensibility
    floor for any perf claim."""
    import time
    laps = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        laps.append((time.perf_counter() - t0) / inner * 1e3)
    laps.sort()
    return {
        "median_ms": round(laps[len(laps) // 2], 4),
        "min_ms": round(laps[0], 4),
        "max_ms": round(laps[-1], 4),
        "spread_pct": round((laps[-1] - laps[0]) / laps[len(laps) // 2]
                            * 100, 1),
        "repeats": repeats,
    }


def bench_kernel(args) -> None:
    """Kernel-level attention microbench with a repeat-median protocol:
    fwd+bwd through the packed family (char-GPT shapes) and the unpacked
    resident family (124M-ish shapes), each as median over --repeats
    laps with min/max spread. Every kernel perf row added to
    benchmarks/RESULTS.md should come from this mode."""
    import jax
    import jax.numpy as jnp

    from replicatinggpt_tpu.ops.flash_pallas import (
        packed_supported, pallas_flash_attention,
        pallas_flash_attention_packed)

    repeats, inner = max(args.repeats, 1), max(args.kernel_inner, 1)
    results = {}

    def fwd_bwd_lap(grad_fn, x):
        def lap():
            for _ in range(inner):
                l, _ = grad_fn(x)
            jax.device_get(l)
        return lap

    # packed family at char-GPT shapes
    B, T, H, D = 64, 256, 6, 64
    C = H * D
    if packed_supported(T, C, H, 2):
        qkv = jax.random.normal(jax.random.PRNGKey(0), (B, T, 3 * C),
                                jnp.bfloat16)
        g = jax.jit(jax.value_and_grad(lambda q: jnp.sum(
            pallas_flash_attention_packed(q, H).astype(jnp.float32) ** 2)))
        jax.device_get(g(qkv)[0])  # compile + warm
        results["packed_char_B64_T256_H6_D64"] = _repeat_median(
            fwd_bwd_lap(g, qkv), repeats=repeats, inner=inner)
        log(f"packed char shapes: {results['packed_char_B64_T256_H6_D64']}")

    # unpacked resident family at the round-2 noise workload
    BH, T2, D2 = 192, 1024, 64
    qkv2 = [jax.random.normal(jax.random.PRNGKey(i), (BH // 6, 6, T2, D2),
                              jnp.bfloat16) for i in range(3)]
    g2 = jax.jit(jax.value_and_grad(lambda q, k, v: jnp.sum(
        pallas_flash_attention(q, k, v).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))
    jax.device_get(g2(*qkv2)[0])
    results["unpacked_BH192_T1024_D64"] = _repeat_median(
        fwd_bwd_lap(lambda x: g2(*x), qkv2), repeats=repeats, inner=inner)
    log(f"unpacked 124M-ish shapes: {results['unpacked_BH192_T1024_D64']}")

    # streamed head-group (packed long-T) vs the unpacked streamed family
    # including its layout round trip — the end-to-end-relevant A/B for
    # sequences past GROUP_STRIP_BYTES (longctx-bench shapes: H=4, D=64)
    if args.kernel_longt:
        Tl, Hl, Dl = args.kernel_longt, 4, 64
        Cl = Hl * Dl
        from replicatinggpt_tpu.ops.flash_pallas import \
            packed_group_stream_supported
        # the family override below bypasses the envelope gate, and the
        # pallas grid would silently truncate an unaligned T
        assert packed_group_stream_supported(Tl, Cl, Hl, 2), \
            f"--kernel-longt must be a multiple of 128, got {Tl}"
        qkv3 = jax.random.normal(jax.random.PRNGKey(7), (1, Tl, 3 * Cl),
                                 jnp.bfloat16)
        gp = jax.jit(jax.value_and_grad(lambda q: jnp.sum(
            pallas_flash_attention_packed(q, Hl, family="group_stream")
            .astype(jnp.float32) ** 2)))
        jax.device_get(gp(qkv3)[0])
        results[f"group_stream_T{Tl}_H4_D64"] = _repeat_median(
            fwd_bwd_lap(gp, qkv3), repeats=repeats, inner=inner)
        log(f"group_stream T={Tl}: {results[f'group_stream_T{Tl}_H4_D64']}")

        def unpacked_from_qkv(qkv):
            q, k, v = jnp.split(qkv, 3, -1)
            B_, T_ = qkv.shape[:2]
            q, k, v = (t.reshape(B_, T_, Hl, Dl).transpose(0, 2, 1, 3)
                       for t in (q, k, v))
            o = pallas_flash_attention(q, k, v)
            o = o.transpose(0, 2, 1, 3).reshape(B_, T_, Cl)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        gu = jax.jit(jax.value_and_grad(unpacked_from_qkv))
        jax.device_get(gu(qkv3)[0])
        results[f"unpacked_stream_T{Tl}_H4_D64"] = _repeat_median(
            fwd_bwd_lap(gu, qkv3), repeats=repeats, inner=inner)
        log(f"unpacked+layout T={Tl}: "
            f"{results[f'unpacked_stream_T{Tl}_H4_D64']}")

    key = ("packed_char_B64_T256_H6_D64"
           if "packed_char_B64_T256_H6_D64" in results
           else "unpacked_BH192_T1024_D64")
    emit({
        "metric": "flash_kernel_fwdbwd_median_ms",
        "value": results[key]["median_ms"],
        "unit": "ms",
        "vs_baseline": 0.0,  # reference has no kernel-level numbers
        "configs": results,
    })


def bench_train(args) -> None:
    import jax
    import numpy as np

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.data.dataset import TokenDataset, load_corpus
    from replicatinggpt_tpu.data.loader import RandomBatcher, prefetch
    from replicatinggpt_tpu.tokenizers import get_tokenizer
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.train.steps import (make_train_scan,
                                                make_train_step)

    cfg = get_config(args.preset)
    mcfg, tcfg = cfg.model, cfg.train
    if args.loss_chunk is not None:
        import dataclasses
        mcfg = dataclasses.replace(mcfg, loss_chunk=args.loss_chunk)
        log(f"loss_chunk: {args.loss_chunk}")
    B, T = args.batch_size, mcfg.block_size
    dev = jax.devices()[0]
    log(f"benchmark device: {dev.platform} ({dev.device_kind}), "
        f"model {mcfg.n_layer}L/{mcfg.n_head}H/{mcfg.n_embd}C "
        f"T={T} B={B} dtype={mcfg.dtype}")

    # real input pipeline: tokenized Tiny Shakespeare, random windows
    text = load_corpus(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    cfg.dataset))
    tok = get_tokenizer(cfg.tokenizer, corpus_text=text)
    ds = TokenDataset.from_text(text, tok, tcfg.val_fraction)
    batcher = RandomBatcher(ds.train, B, T, seed=tcfg.seed)

    state = create_train_state(jax.random.PRNGKey(tcfg.seed), mcfg, tcfg)
    k = max(args.steps_per_dispatch, 1)
    # narrow transfer dtype: token ids fit uint8/uint16 for every preset
    # vocab; 2-4x less H2D traffic (the tunnel's bandwidth is precious),
    # widened to int32 on device inside the jitted step (steps.loss_fn)
    wire = (np.uint8 if mcfg.vocab_size <= 0xff
            else np.uint16 if mcfg.vocab_size <= 0xffff else np.int32)
    if k > 1:
        run = make_train_scan(mcfg, tcfg, k)
        def stacked():
            xs, ys = zip(*(batcher.next_batch() for _ in range(k)))
            return np.stack(xs).astype(wire), np.stack(ys).astype(wire)
        batches = prefetch(iter(stacked, None), depth=2)
    else:
        run = make_train_step(mcfg, tcfg)
        batches = prefetch(iter(batcher), depth=2)
    # round the requested counts UP to whole dispatches and report what
    # actually runs (tps is computed over the actual count either way)
    n_dispatch = -(-args.steps // k)
    n_warmup = -(-args.warmup // k) if args.warmup > 0 else 0
    if (n_dispatch * k, n_warmup * k) != (args.steps, args.warmup):
        log(f"note: measuring {n_dispatch * k} steps / warming up "
            f"{n_warmup * k} (rounded up to whole {k}-step dispatches)")

    log(f"compiling... ({k} steps/dispatch)")
    t0 = time.perf_counter()
    warm_metrics = None
    for _ in range(n_warmup):
        state, warm_metrics = run(state, next(batches))
    if warm_metrics is not None:
        # one real fetch of the LAST dispatch blocks on the whole warmup
        # queue (device execution is in-order) — real fetch, not
        # block_until_ready: the axon backend's block_until_ready
        # returns early (verify-skill finding)
        jax.device_get(warm_metrics["loss"])
    log(f"warmup done in {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    for _ in range(n_dispatch):
        state, metrics = run(state, next(batches))
    loss = float(np.asarray(jax.device_get(metrics["loss"])).ravel()[-1])
    dt = time.perf_counter() - t0
    tps = B * T * n_dispatch * k / dt
    step_ms = dt / (n_dispatch * k) * 1e3
    log(f"{n_dispatch * k} steps in {dt:.2f}s -> {tps:,.0f} tok/s/chip, "
        f"loss {loss:.4f}")
    assert np.isfinite(loss), f"non-finite loss {loss}"

    # dispatch/compute split: a few single-step dispatches, each blocked by
    # a real loss fetch, give per-step latency with full host round-trip;
    # the scan number above amortizes it over k steps
    extra: dict = {}
    try:
        single = make_train_step(mcfg, tcfg)
        xb, yb = batcher.next_batch()
        b1 = (xb.astype(wire), yb.astype(wire))
        state2, m2 = single(state, b1)
        jax.device_get(m2["loss"])  # compile + warm
        t1 = time.perf_counter()
        n1 = 3
        for _ in range(n1):
            state2, m2 = single(state2, b1)
            jax.device_get(m2["loss"])
        blocked_ms = (time.perf_counter() - t1) / n1 * 1e3
        extra["blocked_step_ms"] = round(blocked_ms, 2)
        extra["dispatch_overhead_ms"] = round(max(blocked_ms - step_ms, 0.0),
                                              2)
        log(f"dispatch split: {step_ms:.2f} ms/step amortized (k={k}) vs "
            f"{blocked_ms:.2f} ms blocked single-step")
    except Exception as e:  # diagnostic only — never sink the artifact
        log(f"dispatch-split measurement failed: {e!r}")

    if not args.no_generate:
        try:
            extra.update(measure_generate_p50(mcfg, tcfg))
        except Exception as e:
            log(f"generate measurement failed: {e!r}")

    if args.skip_baseline:
        base = 0.0
        if os.path.exists(CACHE_PATH):
            try:
                with open(CACHE_PATH) as f:
                    base = json.load(f).get(_baseline_key(mcfg, B), 0.0)
            except (OSError, ValueError):   # no cache: no baseline column
                base = 0.0
    else:
        try:
            base = torch_cpu_baseline(mcfg, B, args.remeasure_baseline)
        except Exception as e:
            log(f"torch-CPU baseline failed: {e!r}")
            base = 0.0

    flops_tok = train_flops_per_token(mcfg)
    peak = peak_flops_per_sec(dev.device_kind)
    mfu = tps * flops_tok / peak if peak else None
    if mfu is not None:
        log(f"MFU: {mfu * 100:.1f}% of {peak / 1e12:.0f} TF/s bf16 peak "
            f"({flops_tok / 1e6:.2f} MFLOPs/token)")

    emit({
        "metric": "char_gpt_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps / base, 2) if base > 0 else 0.0,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "step_ms": round(step_ms, 3),
        "steps_per_dispatch": k,
        "final_loss": round(loss, 4),
        "train_flops_per_token": round(flops_tok),
        "mfu": round(mfu, 4) if mfu is not None else None,
        # recovery counters (faults/supervise + checkpoint integrity):
        # the bench loop runs unsupervised with no checkpointing, so a
        # healthy round reports zeros — the keys exist so the BENCH
        # trajectory can see a round that was NOT healthy (a non-finite
        # loss now raises instead of silently finishing)
        "recovery": {"rollbacks": 0, "data_skips": 0, "ckpt_fallbacks": 0},
        **extra,
    })


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="char-gpt")
    p.add_argument("--mode", default="train",
                   choices=["train", "generate", "longctx", "kernel",
                            "decode", "serve", "fleet"])
    p.add_argument("--fleet-replicas", type=int, default=2,
                   help="--mode fleet: engine replicas behind the "
                        "prefix-affinity router")
    p.add_argument("--fleet-sessions", type=int, default=24,
                   help="--mode fleet: multi-turn sessions in the "
                        "load-generator trace")
    p.add_argument("--fleet-turns", type=int, default=3,
                   help="--mode fleet: turns per session (each turn "
                        "re-enters with the whole history — the "
                        "prefix-cache / affinity traffic shape)")
    p.add_argument("--fleet-prefix-groups", type=int, default=3,
                   help="--mode fleet: distinct shared system prefixes")
    p.add_argument("--fleet-prefix-len", type=int, default=32,
                   help="--mode fleet: shared-prefix length in tokens "
                        "(clamped to block_size // 4)")
    p.add_argument("--fleet-kill-at", type=int, default=-1,
                   help="--mode fleet: inject replica_kill of replica 0 "
                        "at this router step (-1 = no chaos); the "
                        "journal-requeue path then runs inside the "
                        "measured replay. With --multiproc this is a "
                        "REAL SIGKILL of worker 0's process")
    p.add_argument("--multiproc", action="store_true",
                   help="--mode fleet: run the replicas as real worker "
                        "PROCESSES (serve-worker subprocesses over "
                        "serve/rpc.py under the faults/procsup.py "
                        "supervisor, RPC registration, private journal "
                        "dirs); the artifact gains per-worker "
                        "pid/restart counts and requeue latency")
    p.add_argument("--net-chaos", action="store_true",
                   help="--mode fleet --multiproc: install the network "
                        "fault ladder (faults/netchaos.py) on the "
                        "fleet RPC wire mid-run — duplicated and "
                        "reordered submit frames, delayed/dropped "
                        "step frames, a one-way partition — and tag "
                        "the artifact net_chaos; the router's "
                        "idempotency keys, reply caches and "
                        "ack/redelivery must absorb all of it "
                        "(rpc_dup_suppressed et al. land in the "
                        "artifact's router block)")
    p.add_argument("--fleet-host-loss", action="store_true",
                   help="--mode fleet --multiproc: upgrade "
                        "--fleet-kill-at to host_loss chaos (SIGKILL "
                        "+ the worker's journal/workdir DELETED) — "
                        "recovery must come from the router's own "
                        "request ledger, nothing on the worker's "
                        "filesystem survives")
    p.add_argument("--fleet-load-step", action="store_true",
                   help="--mode fleet: the autoscaler preset (implies "
                        "--multiproc): start ONE worker, run the "
                        "load-step session trace (arrival rate "
                        "doubles mid-run, then halves), autoscale up "
                        "to --fleet-replicas workers on sustained "
                        "backlog and drain back down on the lull; the "
                        "artifact emits scale-up/scale-down counts, "
                        "peak/final worker counts and the zero-drop "
                        "verification")
    p.add_argument("--disagg", action="store_true",
                   help="--mode fleet: run the disaggregation A/B "
                        "instead of the plain replay — the same mixed "
                        "long+short trace through a colocated fleet "
                        "and a 1-prefill/(N-1)-decode fleet at equal "
                        "worker count; the artifact's disagg_ab block "
                        "carries both arms' short-prompt TTFT, the "
                        "page-transfer counters, and the greedy "
                        "token-identity bit")
    p.add_argument("--fleet-journal-dir", default="",
                   help="--mode fleet: per-replica crash journals "
                        "(default: a temp dir)")
    p.add_argument("--serve-requests", type=int, default=64,
                   help="--mode serve: trace length")
    p.add_argument("--serve-rate", type=float, default=200.0,
                   help="--mode serve: Poisson arrival rate, req/s")
    p.add_argument("--serve-pool", type=int, default=8,
                   help="--mode serve: KV-cache pool slots")
    p.add_argument("--serve-max-new-tokens", type=int, default=32,
                   help="--mode serve: per-request decode budget")
    p.add_argument("--serve-prefix-trace", action="store_true",
                   help="--mode serve: shared-prefix trace (every prompt "
                        "shares one system-prompt-style prefix), replayed "
                        "with the radix prefix cache ON and OFF — the "
                        "artifact carries the TTFT A/B and prefix metrics")
    p.add_argument("--serve-page-size", type=int, default=0,
                   help="--mode serve: tokens per KV page "
                        "(0 = min(16, block_size))")
    p.add_argument("--serve-n-pages", type=int, default=0,
                   help="--mode serve: physical KV pages (0 = "
                        "pool * pages-per-slot, the contiguous pool's HBM)")
    p.add_argument("--decode-window", type=int, default=8,
                   help="--mode serve: decode steps rolled into one "
                        "jitted dispatch at steady state (the async "
                        "engine window; 1 = the blocked per-token "
                        "loop). When > 1 the artifact carries the "
                        "dispatch split: blocked (k=1) vs amortized "
                        "host-overhead per token on the same trace")
    p.add_argument("--decode-window-auto", action="store_true",
                   help="--mode serve: auto-tune the window size from "
                        "the live dispatch split (bounded additive "
                        "increase over warm power-of-two buckets up "
                        "to --decode-window; never recompiles)")
    p.add_argument("--kv-quant", default="none",
                   choices=["none", "int8", "fp8"],
                   help="--mode serve: paged KV page storage precision "
                        "(quant/ — int8/fp8 pages + per-row scales "
                        "halve bytes/page; see --quant-ab for the "
                        "fixed-HBM capacity A/B)")
    p.add_argument("--weight-quant", default="none",
                   choices=["none", "int8", "fp8"],
                   help="--mode serve: block matmul kernel precision "
                        "(absmax-per-channel, dequant fused into the "
                        "matmuls)")
    p.add_argument("--paged-kernel", action="store_true",
                   help="--mode serve: run the unified Pallas "
                        "paged-attention kernel family for every "
                        "engine step (decode, mixed windows, verify; "
                        "shard_map on a >1 mesh) — the artifact's "
                        "kernel_route block records the decision and "
                        "any envelope fallback reasons")
    p.add_argument("--act-quant", default="none",
                   choices=["none", "int8"],
                   help="--mode serve: W8A8 activation quantization "
                        "into the int8 weight matmuls (requires "
                        "--weight-quant int8)")
    p.add_argument("--quant-ab", action="store_true",
                   help="--mode serve: bf16-vs-int8 KV capacity + "
                        "divergence A/B at a FIXED HBM budget on the "
                        "shared-prefix trace — each arm's pool sized "
                        "in its own pages (the admission currency), "
                        "greedy streams compared token-for-token; "
                        "emits the quant_ab artifact block")
    p.add_argument("--serve-storm-trace", action="store_true",
                   help="--mode serve: also replay the admission-heavy "
                        "saturating storm (short prompts, mixed "
                        "deadlines + mid-flight cancels) at the "
                        "configured window AND blocked k=1 — the "
                        "continuous-window acceptance workload. The "
                        "artifact's admission_storm block carries the "
                        "dispatch-count amortization under the storm, "
                        "the idle reference, and the retention ratio "
                        "(>= 0.90 is the ISSUE 13 acceptance bar)")
    p.add_argument("--mesh-shape", default="1x1",
                   help="--mode serve: serving mesh DATAxMODEL (e.g. "
                        "2x2) — the engine runs GSPMD-sharded over a "
                        "(data, model) mesh: paged KV pages over data "
                        "(aggregate capacity at fixed per-chip HBM), "
                        "Megatron TP over model; the artifact carries "
                        "mesh_shape / pages_per_chip / aggregate_pages. "
                        "Downgrades to 1x1 with a log line when the "
                        "backend has fewer devices")
    p.add_argument("--trace-out", default=None,
                   help="--mode serve: write a Perfetto-loadable Chrome "
                        "trace of the replay (one span tree per request "
                        "on per-slot tracks; docs/observability.md) — "
                        "path lands in the artifact JSON")
    p.add_argument("--metrics-timeline", default=None,
                   help="--mode serve: write a JSONL time series of "
                        "every engine counter/gauge/histogram")
    p.add_argument("--metrics-out", default=None,
                   help="--mode serve: write end-of-run metrics as "
                        "Prometheus text exposition")
    p.add_argument("--spec", action="store_true",
                   help="--mode serve: speculative decoding over a "
                        "repetitive greedy trace (n-gram drafter unless "
                        "--draft-model is given)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="--mode serve --spec: drafted tokens per slot "
                        "per step (static; one verify program per k)")
    p.add_argument("--draft-model", default="",
                   help="--mode serve --spec: preset sizing a small "
                        "random-init draft model (vocab/block forced to "
                        "the target's); empty = n-gram drafter")
    p.add_argument("--loss-chunk", type=int, default=None,
                   help="train modes: chunked CE head override "
                        "(ModelConfig.loss_chunk; 0 = one-shot logits)")
    p.add_argument("--decode-cache-layout", default="",
                   choices=["", "heads", "packed"],
                   help="--mode decode: KV-cache layout override "
                        "(ModelConfig.decode_cache_layout)")
    p.add_argument("--decode-batch-sizes", default="1,8,32",
                   help="--mode decode: comma-separated batch sizes for "
                        "the aggregate-throughput sweep")
    p.add_argument("--longctx-t", type=int, default=32768,
                   help="sequence length for --mode longctx")
    p.add_argument("--repeats", type=int, default=7,
                   help="--mode kernel: timed laps per config (median + "
                        "spread reported; >= 5 for defensible claims)")
    p.add_argument("--kernel-inner", type=int, default=20,
                   help="--mode kernel: dispatched iterations per lap")
    p.add_argument("--kernel-longt", type=int, default=0,
                   help="--mode kernel: also A/B the streamed head-group "
                        "(packed) family vs the unpacked streamed family "
                        "+ layout round trip at this T (0 = off)")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--steps-per-dispatch", type=int, default=25,
                   help="lax.scan K optimizer steps per device dispatch "
                        "(amortizes host->device round-trip latency, which "
                        "dominates small-model step time on tunneled TPUs)")
    p.add_argument("--rng-impl", default="rbg",
                   choices=["threefry2x32", "rbg"],
                   help="dropout PRNG; rbg uses the TPU hardware generator "
                        "(~15%% faster steps at dropout 0.2; same mask "
                        "distribution, different bits than threefry)")
    p.add_argument("--remeasure-baseline", action="store_true")
    p.add_argument("--skip-baseline", action="store_true",
                   help="report vs_baseline from cache or 0 if absent")
    p.add_argument("--no-generate", action="store_true",
                   help="skip the embedded generate-p50 measurement")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. 'cpu'); note the "
                        "JAX_PLATFORMS env var is overridden by PJRT "
                        "plugins in some environments — this flag uses "
                        "jax.config, which always wins")
    p.add_argument("--sanitize", action="store_true",
                   help="run the benched mode under GRAFT_SANITIZE "
                        "(jax tracer-leak + NaN checks; numbers are NOT "
                        "comparable to unsanitized runs — the JSON "
                        "artifact is tagged sanitize=true)")
    p.add_argument("--probe-tries", type=int, default=5)
    p.add_argument("--probe-wait", type=float, default=60.0)
    p.add_argument("--watchdog", type=float, default=1500.0,
                   help="hard wall-clock budget (s); past it the error "
                        "artifact is emitted and the process exits")
    args = p.parse_args()

    metric = {"generate": "generate_1k_tokens_per_sec_p50",
              "longctx": f"longctx_t{args.longctx_t}_train_tokens_per_sec"
                         "_per_chip",
              "kernel": "flash_kernel_fwdbwd_median_ms",
              "decode": "generate_batched_aggregate_tokens_per_sec_p50",
              "serve": "serve_replay_aggregate_tokens_per_sec",
              "fleet": "fleet_replay_aggregate_tokens_per_sec",
              "train": "char_gpt_train_tokens_per_sec_per_chip"}[args.mode]
    unit = ("tokens/sec" if args.mode in ("generate", "decode", "serve",
                                          "fleet")
            else "ms" if args.mode == "kernel" else "tokens/sec/chip")
    try:
        # probe first, watchdog after: the probe phase is already
        # hard-bounded (tries x (120s timeout + wait)) and a wedged
        # tunnel can eat many retries — starting the watchdog before it
        # burned the whole run budget on probes and emitted a false
        # "device hang" artifact while the device was merely unclaimed
        try:
            probe_backend(args.platform, args.probe_tries, args.probe_wait)
        except RuntimeError as probe_err:
            # a wedged accelerator tunnel must not zero the artifact: a
            # CPU-tagged measurement still carries signal (BENCH_r01..r05
            # were all zeros from exactly this failure mode). The CPU
            # backend initializes in-process, but probe it anyway — if
            # even CPU fails, something bigger is wrong and the error
            # artifact is the honest outcome.
            log(f"backend probe exhausted retries ({probe_err}); "
                f"falling back to JAX_PLATFORMS=cpu")
            probe_backend("cpu", 1, 0.0)
            args.platform = "cpu"
            _EMIT_TAGS["backend"] = "cpu-fallback"
            _EMIT_TAGS["backend_error"] = str(probe_err)[:200]
        start_watchdog(args.watchdog, metric, unit)
        import jax

        if args.platform:
            jax.config.update("jax_platforms", args.platform)
        jax.config.update("jax_default_prng_impl", args.rng_impl)
        import contextlib
        san = contextlib.nullcontext()
        if args.sanitize:
            # env first so Engine/runner construction sees it; the
            # context flips jax's leak/NaN checks for the whole mode
            os.environ["GRAFT_SANITIZE"] = "1"
            from replicatinggpt_tpu.utils.sanitize import sanitized
            san = sanitized(True)
            log("GRAFT_SANITIZE: tracer-leak + NaN checks on (numbers "
                "not comparable to unsanitized runs)")
        with san:
            if args.mode == "generate":
                bench_generate(args)
            elif args.mode == "longctx":
                bench_longctx(args)
            elif args.mode == "kernel":
                bench_kernel(args)
            elif args.mode == "decode":
                bench_decode_sweep(args)
            elif args.mode == "serve":
                bench_serve(args)
            elif args.mode == "fleet":
                bench_fleet(args)
            else:
                bench_train(args)
    except BaseException as e:  # noqa: BLE001 — artifact must still emit
        log(f"bench failed: {e!r}")
        emit(error_payload(metric, unit, repr(e)))
        raise SystemExit(1)


if __name__ == "__main__":
    main()
