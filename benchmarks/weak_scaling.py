#!/usr/bin/env python
"""Weak-scaling harness: per-chip throughput vs mesh size at fixed
per-chip batch.

BASELINE.json's scaling target (">90% weak-scaling efficiency v4-8 ->
v4-32") needs a measurement procedure; this is it. For each requested
device count n the same per-chip workload (batch_per_chip x block_size
char-GPT train steps, DP sharding, optional FSDP) runs on an n-device
mesh and reports tokens/sec/chip; efficiency is tok/s/chip(n) divided by
tok/s/chip(smallest n).

Each n runs in a SUBPROCESS because the device count is fixed at backend
init: on CPU the child forces `jax_num_cpu_devices=n` (the virtual-mesh
trick from tests/conftest.py — measures the sharding/collective
*structure*, not ICI bandwidth); on TPU the child uses the real devices
and `n` must not exceed `jax.device_count()`.

Usage:
    python benchmarks/weak_scaling.py --devices 1,2,4,8 --platform cpu
    python benchmarks/weak_scaling.py --devices 4 --steps 30 --platform ''
    # (--platform '' = real devices; the default 'cpu' forces the
    #  virtual mesh even on a TPU VM)

Prints one JSON line: {"metric": "weak_scaling_efficiency", ...} with
the per-n table embedded.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_CHILD = r"""
import dataclasses, json, sys, time
import jax

n = int(sys.argv[1])
platform = sys.argv[2]
batch_per_chip = int(sys.argv[3])
steps = int(sys.argv[4])
preset = sys.argv[5]
fsdp = sys.argv[6] == "1"
attention = sys.argv[7]          # '' = preset default
remat = sys.argv[8]              # '' = preset default, '0'/'1' override

if platform:
    jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        jax.config.update("jax_num_cpu_devices", n)
assert len(jax.devices()) >= n, (n, jax.devices())

import numpy as np

from replicatinggpt_tpu.config import MeshConfig, get_config
from replicatinggpt_tpu.parallel import select_attention_fn
from replicatinggpt_tpu.parallel.mesh import (make_batch_sharding, make_mesh,
                                              shard_train_state)
from replicatinggpt_tpu.train.state import create_train_state
from replicatinggpt_tpu.train.steps import make_train_step

cfg = get_config(preset)
mcfg, tcfg = cfg.model, cfg.train
if attention:
    mcfg = dataclasses.replace(mcfg, attention_impl=attention)
if remat:
    mcfg = dataclasses.replace(mcfg, remat=remat == "1")
B = batch_per_chip * n
mesh_cfg = MeshConfig(data=n, fsdp=fsdp)
mesh = make_mesh(mesh_cfg)
state = shard_train_state(
    lambda: create_train_state(jax.random.PRNGKey(0), mcfg, tcfg),
    mesh, mesh_cfg)
# the mesh-aware attention core (e.g. the shard_map flash wrapper for
# explicit 'flash') — exactly what train.runner would select
attention_fn = select_attention_fn(mcfg, mesh_cfg, mesh)
step = make_train_step(mcfg, tcfg, donate=False, attention_fn=attention_fn)
rng = np.random.default_rng(0)
bs = make_batch_sharding(mesh)
toks = rng.integers(0, mcfg.vocab_size, (B, mcfg.block_size + 1),
                    dtype=np.int32)
batch = (jax.device_put(toks[:, :-1], bs),   # next-token targets,
         jax.device_put(toks[:, 1:], bs))    # as real training
# AOT compile so the artifact records compile time and the compiler's
# own per-device memory accounting (the numbers a pod-slice run needs
# to know in advance)
t0 = time.perf_counter()
lowered = step.lower(state, batch)
compiled = lowered.compile()
compile_s = time.perf_counter() - t0
mem = {}
try:
    ma = compiled.memory_analysis()
    # donate=False above means the step's outputs (the new train state)
    # are fresh buffers live alongside the arguments at peak — include
    # them, or the pod-planning estimate understates true peak usage.
    mem = {"temp_bytes": int(ma.temp_size_in_bytes),
           "argument_bytes": int(ma.argument_size_in_bytes),
           "output_bytes": int(ma.output_size_in_bytes),
           "peak_estimate_gb": round((ma.temp_size_in_bytes
                                      + ma.argument_size_in_bytes
                                      + ma.output_size_in_bytes)
                                     / 2**30, 3)}
except Exception as e:  # backend without memory_analysis
    mem = {"memory_analysis_error": str(e)[:120]}
attention_name = ("none (GSPMD local core)" if attention_fn is None
                  else getattr(attention_fn, "impl_name", "custom"))
if steps < 0:
    # compile-only: the program's buffers never allocate — the mode for
    # programs whose FULL-mesh memory exceeds this single host (e.g.
    # 350M no-remat FSDP x 16: a pod slice holds it across chips; one
    # process simulating all 16 devices cannot)
    row = {"n": n, "tokens_per_sec_per_chip": 0.0,
           "compile_s": round(compile_s, 1), "step_s": None,
           "compile_only": True,
           "attention_fn": attention_name,
           **mem}
    print(json.dumps(row))
    sys.exit(0)
t0 = time.perf_counter()
state, m = compiled(state, batch)
assert np.isfinite(float(jax.device_get(m["loss"])))  # warm + validate
warm_s = time.perf_counter() - t0
if steps > 0:
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = compiled(state, batch)
    float(jax.device_get(m["loss"]))
    dt = (time.perf_counter() - t0) / steps
else:
    # steps=0: the validation step is the measurement (big presets on a
    # 1-core virtual mesh cost minutes per step; compile time + memory
    # are the artifact's point there)
    dt = warm_s
tps_chip = B * mcfg.block_size / dt / n
row = {"n": n, "tokens_per_sec_per_chip": tps_chip,
       "compile_s": round(compile_s, 1), "step_s": round(dt, 3),
       "attention_fn": attention_name,
       **mem}
print(json.dumps(row))
"""


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--devices", default="1,2,4,8",
                   help="comma-separated mesh sizes")
    p.add_argument("--platform", default="cpu",
                   help="'cpu' = virtual mesh (structure only); '' = "
                        "whatever backend jax picks (real TPUs)")
    p.add_argument("--batch-per-chip", type=int, default=8)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--preset", default="test-tiny")
    p.add_argument("--fsdp", action="store_true")
    p.add_argument("--attention", default="",
                   help="override attention_impl (e.g. 'flash' to route "
                        "the shard_map wrapper on the virtual mesh)")
    p.add_argument("--remat", default="",
                   help="'0'/'1' to override the preset's remat flag "
                        "(e.g. '0' rehearses the pod-slice no-remat FSDP "
                        "program)")
    p.add_argument("--out", default="",
                   help="also write the JSON artifact to this path")
    p.add_argument("--timeout", type=float, default=600.0)
    args = p.parse_args()

    rows = []
    skipped = []
    requested = [int(x) for x in args.devices.split(",")]
    for n in requested:
        try:
            r = subprocess.run(
                [sys.executable, "-c", _CHILD, str(n), args.platform,
                 str(args.batch_per_chip), str(args.steps), args.preset,
                 "1" if args.fsdp else "0", args.attention, args.remat],
                capture_output=True, text=True, timeout=args.timeout,
                cwd=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
        except subprocess.TimeoutExpired:
            print(f"n={n} timed out after {args.timeout:.0f}s; skipping",
                  file=sys.stderr)
            skipped.append(n)
            continue
        if r.returncode != 0:
            print(f"n={n} failed:\n{r.stderr.strip()[-800:]}",
                  file=sys.stderr)
            skipped.append(n)
            continue
        row = json.loads(r.stdout.strip().splitlines()[-1])
        rows.append(row)
        if row.get("compile_only"):
            print(f"n={row['n']}: compile-only, {row['compile_s']:.0f}s "
                  f"compile", file=sys.stderr)
        else:
            print(f"n={row['n']}: {row['tokens_per_sec_per_chip']:,.0f} "
                  f"tok/s/chip", file=sys.stderr)

    if not rows:
        line = json.dumps({"metric": "weak_scaling_efficiency", "value": 0.0,
                           "unit": "fraction", "error": "all sizes failed",
                           "requested_sizes": requested})
        if args.out:  # the artifact contract holds on failure too
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        raise SystemExit(1)
    base = rows[0]["tokens_per_sec_per_chip"]
    for row in rows:
        row["efficiency"] = (round(row["tokens_per_sec_per_chip"] / base, 4)
                             if base else None)  # compile-only rows
    out = {
        "metric": "weak_scaling_efficiency",
        # None (JSON null) for compile-only rehearsals: 0.0 is the
        # failure artifact's value and would read as catastrophic
        # scaling against the >90% target
        "value": rows[-1]["efficiency"],
        "unit": f"fraction of n={rows[0]['n']} per-chip throughput",
        "platform": args.platform or "default",
        "preset": args.preset,
        "fsdp": args.fsdp,
        "attention": args.attention or "preset-default",
        "remat": args.remat or "preset-default",
        "table": rows,
    }
    if skipped:
        # the efficiency above is normalized against the smallest size
        # that RAN; make missing sizes impossible to miss in the artifact
        out["skipped_sizes"] = skipped
        out["requested_sizes"] = requested
    if args.platform == "cpu":
        # n virtual devices timeshare one host's cores, so per-chip
        # throughput divides by ~n — the efficiency number here validates
        # only that the sharded program compiles/runs at every size; real
        # efficiency requires real chips (run with --platform '')
        out["note"] = ("virtual CPU mesh: efficiency reflects host-core "
                       "contention, not interconnect scaling")
    line = json.dumps(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)


if __name__ == "__main__":
    main()
