#!/usr/bin/env python
"""Ring vs Ulysses sequence-parallel attention measurement.

Times fwd+bwd of both seq-parallel cores over a virtual device mesh at a
sweep of sequence lengths, and prints one JSON line per (impl, T) plus a
recommendation. Used to ground `select_attention_fn`'s 'auto' policy in
measurement instead of convention (the committed results live in
benchmarks/SEQ_PARALLEL.md).

Run on CPU (8 virtual devices) by default; on a real multi-chip TPU slice
drop --platform and the same sweep measures ICI for real.

  python benchmarks/seq_parallel_bench.py --platform cpu \
      --seq-lens 4096 8192

Analytic context the numbers sit in (per device, per attention call,
n = seq-axis size, local chunk Tl = T/n):
- ring: n-1 ppermute hops moving the (B, H, Tl, D) K and V chunks —
  ~2(n-1)·B·H·Tl·D elements total, overlapped with the per-hop block
  matmul; score tiles are (Tl, Tl); the local core is dense einsum.
- Ulysses: two all-to-alls (three in, one out) moving
  ~4·(n-1)/n·B·H·Tl·D elements — ~n/2 x less traffic than the ring —
  after which each device holds H/n heads over the FULL sequence, so the
  local core can be the Pallas flash kernel (O(T) memory) on TPU.
  Requires H % n == 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--platform", default=None)
    p.add_argument("--n-devices", type=int, default=8)
    p.add_argument("--seq-lens", type=int, nargs="+",
                   default=[4096, 8192],
                   help="default matches the committed SEQ_PARALLEL.md "
                        "sweep (feasible on the 8-device CPU mesh; longer "
                        "lengths are for real multi-chip slices)")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--seq-axis", type=int, default=0,
                   help="seq axis size; 0 = all devices")
    args = p.parse_args()

    if args.platform == "cpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.n_devices}"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np

    from replicatinggpt_tpu.config import MeshConfig
    from replicatinggpt_tpu.parallel.mesh import make_mesh
    from replicatinggpt_tpu.parallel.ring_attention import ring_attention
    from replicatinggpt_tpu.parallel.ulysses import ulysses_attention

    n = args.seq_axis or len(jax.devices())
    mesh = make_mesh(MeshConfig(data=1, seq=n, model=1))
    from jax.sharding import NamedSharding, PartitionSpec as P
    qkv_sharding = NamedSharding(mesh, P(None, None, "seq", None))
    local_impl = "flash" if jax.default_backend() == "tpu" else "einsum"
    log(f"mesh: seq={n} on {jax.default_backend()}; "
        f"Ulysses local impl: {local_impl}")

    results = []
    for T in args.seq_lens:
        shape = (args.batch, args.heads, T, args.head_dim)
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.device_put(jax.random.normal(kk, shape, jnp.bfloat16),
                                  qkv_sharding) for kk in ks)

        def time_impl(name, fn):
            loss = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
                argnums=(0,)))
            try:
                t0 = time.perf_counter()
                g = loss(q, k, v)
                jax.device_get(jax.tree_util.tree_leaves(g)[0][0, 0, 0])
                compile_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                for _ in range(args.steps):
                    g = loss(q, k, v)
                jax.device_get(jax.tree_util.tree_leaves(g)[0][0, 0, 0])
                ms = (time.perf_counter() - t0) / args.steps * 1e3
                rec = {"impl": name, "seq_len": T, "fwd_bwd_ms": round(ms, 2),
                       "compile_s": round(compile_s, 1), "seq_axis": n,
                       "platform": jax.default_backend()}
            except Exception as e:  # OOM at long T is itself a data point
                rec = {"impl": name, "seq_len": T, "fwd_bwd_ms": None,
                       "error": repr(e)[:200], "seq_axis": n,
                       "platform": jax.default_backend()}
            print(json.dumps(rec), flush=True)
            return rec

        results.append(time_impl(
            "ring", lambda q, k, v: ring_attention(q, k, v, mesh=mesh)))
        if args.heads % n == 0:
            results.append(time_impl(
                "ulysses", lambda q, k, v: ulysses_attention(
                    q, k, v, mesh=mesh, impl=local_impl)))

    by_t = {}
    for r in results:
        by_t.setdefault(r["seq_len"], {})[r["impl"]] = r.get("fwd_bwd_ms")

    def winner(d):
        timed = {k: v for k, v in d.items() if v is not None}
        if not timed:
            return None  # nothing measured at this T — no recommendation
        return min(timed, key=timed.get)

    wins = {t: winner(d) for t, d in by_t.items()}
    print(json.dumps({"recommendation": wins}), flush=True)


if __name__ == "__main__":
    main()
