#!/usr/bin/env python
"""Ring-attention long-context rehearsal: AOT-compile the full train step
with the sequence axis sharded over a virtual mesh at a PER-DEVICE shard
length beyond the resident chunk-kernel bound (STREAM_KV_BYTES: 16k rows
at D=64 bf16), recording compile time and the compiler's per-device
memory accounting — the pod-planning numbers for a real long-context
slice, in the style of benchmarks/SCALING_*.json.

The per-hop kernels themselves cannot run under this CPU rehearsal (the
Pallas interpreter unrolls the streamed grid at trace time — a 256x256
tile grid is untraceable), so the rehearsed program uses the q-chunked
einsum hop body; on TPU hardware `_flash_hop_supported` routes the same
hops through the streamed chunk kernels, which are proven on the real
chip separately (benchmarks/RESULTS.md "Ring hops" round-4 section:
fwd+bwd at Tl=32k/64k). What this artifact pins down is the *program*:
the ppermute ring over the seq axis at T_global = n x Tl, its
memory footprint per device, and that it compiles end to end.

Usage:
    python benchmarks/ring_longctx_rehearsal.py --devices 8 \
        --t-local 32768 --out benchmarks/SCALING_ring_longctx.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_CHILD = r"""
import json, sys, time
import jax

n = int(sys.argv[1])
t_local = int(sys.argv[2])
compile_only = sys.argv[3] == "1"

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", n)

import numpy as np

from replicatinggpt_tpu.config import MeshConfig, ModelConfig, TrainConfig
from replicatinggpt_tpu.parallel import select_attention_fn
from replicatinggpt_tpu.parallel.mesh import (make_batch_sharding, make_mesh,
                                              shard_train_state)
from replicatinggpt_tpu.train.state import create_train_state
from replicatinggpt_tpu.train.steps import make_train_step

T = n * t_local
mcfg = ModelConfig(vocab_size=256, block_size=T, n_layer=4, n_head=4,
                   n_embd=256, dropout=0.0, attn_dropout=0.0,
                   dtype="bfloat16", remat=True, attention_impl="ring")
tcfg = TrainConfig(batch_size=1, lr=1e-3)
mesh_cfg = MeshConfig(data=1, seq=n, model=1)
mesh = make_mesh(mesh_cfg)
attention_fn = select_attention_fn(mcfg, mesh_cfg, mesh)
assert attention_fn is not None, "ring attention_fn not selected"

state = shard_train_state(
    lambda: create_train_state(jax.random.PRNGKey(0), mcfg, tcfg),
    mesh, mesh_cfg)
toks = np.random.default_rng(0).integers(0, 256, (1, T + 1), dtype=np.int32)
bs = make_batch_sharding(mesh)
batch = (jax.device_put(toks[:, :-1], bs), jax.device_put(toks[:, 1:], bs))

step = make_train_step(mcfg, tcfg, donate=False, attention_fn=attention_fn)
t0 = time.perf_counter()
lowered = step.lower(state, batch)
compiled = lowered.compile()
compile_s = time.perf_counter() - t0

try:
    ma = compiled.memory_analysis()
    gb = 1024 ** 3
    mem = {
        "temp_gb_per_device": round(ma.temp_size_in_bytes / n / gb, 2),
        "args_gb_per_device": round(ma.argument_size_in_bytes / n / gb, 2),
        "output_gb_per_device": round(ma.output_size_in_bytes / n / gb, 2),
    }
except Exception as e:
    mem = {"memory_analysis_error": str(e)[:120]}

row = {"devices": n, "t_local": t_local, "t_global": T,
       "compile_s": round(compile_s, 1), "compile_only": compile_only,
       "hop_body_rehearsed": "einsum (interpret-mode streamed grid is "
                             "untraceable; TPU routes flash)",
       **mem}
if not compile_only:
    t0 = time.perf_counter()
    state, m = compiled(state, batch)
    loss = float(np.asarray(jax.device_get(m["loss"])))
    row["step_s"] = round(time.perf_counter() - t0, 1)
    row["loss_finite"] = bool(np.isfinite(loss))
print(json.dumps(row))
"""


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--t-local", type=int, default=32768)
    ap.add_argument("--compile-only", action="store_true", default=True)
    ap.add_argument("--execute", dest="compile_only", action="store_false")
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    r = subprocess.run(
        [sys.executable, "-c", _CHILD, str(args.devices), str(args.t_local),
         "1" if args.compile_only else "0"],
        capture_output=True, text=True, env=env)
    if r.returncode != 0:
        sys.stderr.write(r.stderr[-4000:])
        sys.exit(1)
    row = json.loads(r.stdout.strip().splitlines()[-1])
    out = {"metric": "ring_longctx_rehearsal", **row}
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
