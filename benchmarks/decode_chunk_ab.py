"""In-run A/B of the chunked (grow-as-you-go) KV-cache decode vs the
monolithic full-bucket scan, per batch size, in ONE process — both modes
share the model, the tunnel session and the thermal/noise environment,
so the delta is the chunking and not run-to-run drift.

The monolithic arm is the same code with attend_granule = block_size
(one chunk at full width — exactly the pre-chunking program). Repro:

    python benchmarks/decode_chunk_ab.py --preset gpt2-small \
        --batch-sizes 1,8,32 --laps 5

Writes a JSON summary line per (mode, B); RESULTS.md decode rows cite
this script. Capability context: the reference's sampler re-forwards
the whole window per token (/root/reference/GPT1.py:196-212); both arms
here are KV-cached and identical in output (tests pin trajectory
bit-parity), so this measures bytes, not semantics.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-small")
    ap.add_argument("--batch-sizes", default="1,8,32")
    ap.add_argument("--laps", type=int, default=5)
    ap.add_argument("--tokens", type=int, default=1000)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.sample import GenerateConfig, generate
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config(args.preset)
    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)
    shipped_granule = GenerateConfig().attend_granule  # what users get
    out = {}
    for B in (int(b) for b in args.batch_sizes.split(",")):
        prompt = jnp.zeros((B, 1), jnp.int32)
        for mode, granule in (("monolithic", cfg.model.block_size),
                              ("chunked", shipped_granule)):
            # attend_granule is part of the static jit key, so the two
            # arms compile as distinct programs — no cache clearing
            gcfg = GenerateConfig(max_new_tokens=args.tokens, top_k=50,
                                  attend_granule=granule)
            # warm/compile
            jax.device_get(generate(state.params, prompt, cfg.model, gcfg))
            laps = []
            for i in range(args.laps):
                t0 = time.perf_counter()
                toks = generate(state.params, prompt, cfg.model, gcfg,
                                rng=jax.random.PRNGKey(i))
                jax.device_get(toks)  # real fetch; block_until_ready lies
                laps.append(time.perf_counter() - t0)
            p50 = sorted(laps)[len(laps) // 2]  # laps stay chronological
            row = {"p50_ms_per_1k": round(p50 * 1e3 * 1000 / args.tokens, 1),
                   "aggregate_tok_s": round(B * args.tokens / p50, 1),
                   "laps_ms": [round(x * 1e3, 1) for x in laps]}
            out[f"{mode}_B{B}"] = row
            print(f"{mode:>10} B={B}: p50 {row['p50_ms_per_1k']} ms/1k, "
                  f"{row['aggregate_tok_s']:,.0f} tok/s aggregate",
                  flush=True)
    print(json.dumps({"preset": args.preset, "tokens": args.tokens,
                      "results": out}))


if __name__ == "__main__":
    sys.exit(main())
