# Training/inference image for the TPU-native framework.
#
# The reference ships a placeholder (docker/whalesay + fortune|cowsay,
# /root/reference/Dockerfile:1-4) — packaging existed as a gesture only
# (SURVEY.md §2.0 C23). This is the real equivalent: a runnable image with
# the framework, its JAX TPU stack, and the native fastpath toolchain.
#
# Build:  docker build -t replicatinggpt-tpu .
# Train:  docker run --privileged replicatinggpt-tpu \
#             train --preset char-gpt --checkpoint-dir /ckpt
# (TPU VMs need --privileged and the host's /dev accelerator nodes; on a
#  pod slice, run one container per host with --num-processes/--process-id
#  or let the TPU runtime auto-configure jax.distributed.)

FROM python:3.12-slim

# g++ compiles the native host-side fastpath (replicatinggpt_tpu/native/)
# on first import; build-essential keeps that path available in-image.
RUN apt-get update && apt-get install -y --no-install-recommends \
        build-essential \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app

# TPU wheel pulls libtpu; the same image runs on CPU (tests, dry runs).
RUN pip install --no-cache-dir "jax[tpu]" \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir optax orbax-checkpoint regex numpy pytest

COPY replicatinggpt_tpu/ replicatinggpt_tpu/
COPY datasets/ datasets/
COPY tests/ tests/
COPY bench.py ./

# pre-build the native fastpath so first run doesn't pay the compile
RUN python -m replicatinggpt_tpu.native.build

ENTRYPOINT ["python", "-m", "replicatinggpt_tpu"]
CMD ["train", "--preset", "char-gpt"]
